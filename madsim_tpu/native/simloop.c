/* simloop — the compiled executor core of the host tier.
 *
 * The reference's entire simulation loop is compiled Rust
 * (madsim/src/sim/task/mod.rs:220-317 block_on/run_all_ready,
 * time/mod.rs:21-230 TimerHeap, async-task wakers).  This CPython
 * extension is that property for the Python host tier: the per-poll hot
 * sequence — random pop, flag checks, context swap, coroutine step,
 * pollable subscription, jitter advance, timer fire — runs in C, while
 * tasks, nodes and user coroutines stay ordinary Python objects.
 *
 * Determinism contract: pop indices and jitter use the SAME GlobalRng
 * draws in the same order as the pure-Python loop (the Lemire reduction
 * `u64 * n >> 64` on rng.next_u64()), the timer heap orders by
 * (deadline, insertion seq) exactly like the Python heapq path, and
 * Sleep arms its timer lazily on first subscribe, exactly like the
 * Python Sleep.  Schedules are byte-identical with the C core on or off
 * (MADSIM_NO_NATIVE=1 forces it off; tests/test_native.py asserts the
 * transparency).
 *
 * Types:
 *   Future  — one-shot resolvable cell with FIFO waker list (the
 *             futures.Future contract; subclassable, JoinHandle extends
 *             it from Python).
 *   Sleep   — Future + lazily-armed virtual-time timer (time.Sleep).
 *   Timers  — binary heap of (deadline, seq, entry) + the monotonic
 *             virtual clock (time/mod.rs TimerHeap).
 *   TimerEntry — cancelable handle to one registration.
 *   Loop    — the executor driver bound to (executor, ready-list, rng,
 *             timers, thread-local context).
 *
 * Build: g++ -O2 -shared -fPIC -I<python-include> simloop.c -o _simloop.so
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stddef.h>
#include <stdint.h>

/* interned attribute / method names (module-lifetime) */
static PyObject *s_wake, *s_subscribe, *s_scheduled, *s_finished, *s_cancelled,
    *s_node, *s_killed, *s_paused, *s_paused_tasks, *s_coro, *s_task,
    *s__drop_task, *s__complete, *s__poll_raised, *s_ns, *s__ready_items,
    *s_time_limit_ns, *s__raise_time_limit;

static PyObject *instant_cls = NULL; /* set by _configure() from time.py */

/* ------------------------------------------------------------------ Future */

typedef struct {
    PyObject_HEAD
    int state;          /* 0 pending, 1 result, 2 exception */
    PyObject *payload;  /* result value or exception instance */
    PyObject *wakers;   /* PyList of tasks, lazily created */
} FutureObj;

static PyTypeObject Future_Type;
static PyTypeObject Sleep_Type;

/* inlined Task.wake: flag checks + direct ready-list append.  Falls back
 * to the Python method when the task has no direct list (MADSIM_NATIVE's
 * ctypes queue). Task.wake never draws from the rng (the loop's cached
 * cursor relies on this). */
static int
task_wake(PyObject *task)
{
    PyObject *v = PyObject_GetAttr(task, s_finished);
    if (v == NULL)
        return -1;
    int skip = PyObject_IsTrue(v);
    Py_DECREF(v);
    if (skip < 0)
        return -1;
    if (skip)
        return 0;
    v = PyObject_GetAttr(task, s_scheduled);
    if (v == NULL)
        return -1;
    skip = PyObject_IsTrue(v);
    Py_DECREF(v);
    if (skip < 0)
        return -1;
    if (skip)
        return 0;
    PyObject *items = PyObject_GetAttr(task, s__ready_items);
    if (items == NULL) {
        PyErr_Clear(); /* not a task.py Task: generic wake() */
        PyObject *r = PyObject_CallMethodNoArgs(task, s_wake);
        if (r == NULL)
            return -1;
        Py_DECREF(r);
        return 0;
    }
    if (!PyList_Check(items)) {
        Py_DECREF(items);
        PyObject *r = PyObject_CallMethodNoArgs(task, s_wake);
        if (r == NULL)
            return -1;
        Py_DECREF(r);
        return 0;
    }
    if (PyObject_SetAttr(task, s_scheduled, Py_True) < 0) {
        Py_DECREF(items);
        return -1;
    }
    int rc = PyList_Append(items, task);
    Py_DECREF(items);
    return rc;
}

static int
future_wake_all(FutureObj *self)
{
    PyObject *wakers = self->wakers;
    if (wakers == NULL || PyList_GET_SIZE(wakers) == 0)
        return 0;
    self->wakers = NULL; /* detach: re-entrant subscribes build a new list */
    Py_ssize_t n = PyList_GET_SIZE(wakers);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (task_wake(PyList_GET_ITEM(wakers, i)) < 0) {
            Py_DECREF(wakers);
            return -1;
        }
    }
    Py_DECREF(wakers);
    return 0;
}

/* C-level set_result(None)-equivalent used by the timer fire path */
static int
future_resolve_none(FutureObj *self)
{
    if (self->state != 0)
        return 0;
    self->state = 1;
    self->payload = Py_NewRef(Py_None);
    return future_wake_all(self);
}

static PyObject *
future_set_result(FutureObj *self, PyObject *value)
{
    if (self->state != 0)
        Py_RETURN_NONE;
    self->state = 1;
    self->payload = Py_NewRef(value);
    if (future_wake_all(self) < 0)
        return NULL;
    Py_RETURN_NONE;
}

static PyObject *
future_set_exception(FutureObj *self, PyObject *exc)
{
    if (!PyExceptionInstance_Check(exc)) {
        PyErr_SetString(PyExc_TypeError, "set_exception expects an exception instance");
        return NULL;
    }
    if (self->state != 0)
        Py_RETURN_NONE;
    self->state = 2;
    self->payload = Py_NewRef(exc);
    if (future_wake_all(self) < 0)
        return NULL;
    Py_RETURN_NONE;
}

static PyObject *
future_done(FutureObj *self, PyObject *Py_UNUSED(ignored))
{
    return PyBool_FromLong(self->state != 0);
}

static PyObject *
future_result(FutureObj *self, PyObject *Py_UNUSED(ignored))
{
    if (self->state == 1)
        return Py_NewRef(self->payload);
    if (self->state == 2) {
        PyErr_SetRaisedException(Py_NewRef(self->payload));
        return NULL;
    }
    PyErr_SetString(PyExc_RuntimeError, "future is not resolved yet");
    return NULL;
}

static PyObject *
future_exception(FutureObj *self, PyObject *Py_UNUSED(ignored))
{
    if (self->state == 2)
        return Py_NewRef(self->payload);
    Py_RETURN_NONE;
}

static PyObject *
future__reset(FutureObj *self, PyObject *Py_UNUSED(ignored))
{
    /* re-arm a resolved future (Sleep.reset); wakers are kept, matching
     * the Python Future._reset */
    self->state = 0;
    Py_CLEAR(self->payload);
    Py_RETURN_NONE;
}

/* shared by the method and the Loop fast path */
static int
future_subscribe_impl(FutureObj *self, PyObject *task)
{
    if (self->state != 0)
        return task_wake(task);
    if (self->wakers == NULL) {
        self->wakers = PyList_New(0);
        if (self->wakers == NULL)
            return -1;
    }
    int found = PySequence_Contains(self->wakers, task);
    if (found < 0)
        return -1;
    if (!found && PyList_Append(self->wakers, task) < 0)
        return -1;
    return 0;
}

static PyObject *
future_subscribe(FutureObj *self, PyObject *task)
{
    if (future_subscribe_impl(self, task) < 0)
        return NULL;
    Py_RETURN_NONE;
}

/* --- await protocol: the future IS its own iterator ----------------------
 * Stateless per-step (checks the future's state each __next__), so one
 * future shared by several awaiters is fine, and no per-await iterator
 * object is allocated. */

static PyObject *
future_iternext(FutureObj *self)
{
    if (self->state == 0)
        return Py_NewRef((PyObject *)self); /* yield the pollable */
    if (self->state == 1) {
        if (self->payload == Py_None)
            return NULL; /* bare StopIteration == StopIteration(None) */
        PyObject *exc = PyObject_CallFunctionObjArgs(
            PyExc_StopIteration, self->payload, NULL);
        if (exc != NULL)
            PyErr_SetRaisedException(exc);
        return NULL;
    }
    PyErr_SetRaisedException(Py_NewRef(self->payload));
    return NULL;
}

static PyObject *
future_await(FutureObj *self)
{
    return Py_NewRef((PyObject *)self);
}

static PyAsyncMethods future_as_async = {
    .am_await = (unaryfunc)future_await,
};

static int
future_init(FutureObj *self, PyObject *args, PyObject *kwds)
{
    /* accepts no arguments; subclass __init__s call super().__init__() */
    return 0;
}

static int
future_traverse(FutureObj *self, visitproc visit, void *arg)
{
    Py_VISIT(self->payload);
    Py_VISIT(self->wakers);
    return 0;
}

static int
future_clear(FutureObj *self)
{
    Py_CLEAR(self->payload);
    Py_CLEAR(self->wakers);
    return 0;
}

static void
future_dealloc(FutureObj *self)
{
    /* Python subclasses (JoinHandle) reach this through subtype_dealloc,
     * which handles slot teardown and the heap-type DECREF itself. */
    PyObject_GC_UnTrack(self);
    future_clear(self);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *
future_get_wakers(FutureObj *self, void *closure)
{
    /* live view for Python subclasses (time.Sleep checks `_wakers`) */
    if (self->wakers == NULL) {
        self->wakers = PyList_New(0);
        if (self->wakers == NULL)
            return NULL;
    }
    return Py_NewRef(self->wakers);
}

static PyGetSetDef future_getset[] = {
    {"_wakers", (getter)future_get_wakers, NULL, NULL, NULL},
    {NULL}
};

static PyMethodDef future_methods[] = {
    {"done", (PyCFunction)future_done, METH_NOARGS, NULL},
    {"result", (PyCFunction)future_result, METH_NOARGS, NULL},
    {"exception", (PyCFunction)future_exception, METH_NOARGS, NULL},
    {"set_result", (PyCFunction)future_set_result, METH_O, NULL},
    {"set_exception", (PyCFunction)future_set_exception, METH_O, NULL},
    {"_reset", (PyCFunction)future__reset, METH_NOARGS, NULL},
    {"subscribe", (PyCFunction)future_subscribe, METH_O, NULL},
    {NULL}
};

static PyTypeObject Future_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_simloop.Future",
    .tp_basicsize = sizeof(FutureObj),
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_BASETYPE | Py_TPFLAGS_HAVE_GC,
    .tp_new = PyType_GenericNew,
    .tp_init = (initproc)future_init,
    .tp_dealloc = (destructor)future_dealloc,
    .tp_traverse = (traverseproc)future_traverse,
    .tp_clear = (inquiry)future_clear,
    .tp_as_async = &future_as_async,
    .tp_iter = PyObject_SelfIter,
    .tp_iternext = (iternextfunc)future_iternext,
    .tp_methods = future_methods,
    .tp_getset = future_getset,
    .tp_doc = "One-shot resolvable value with deterministic FIFO waker list (C core).",
};

/* -------------------------------------------------------------- TimerEntry */

typedef struct {
    PyObject_HEAD
    int64_t deadline_ns;
    PyObject *target; /* Future to resolve with None, or 0-arg callable */
    char cancelled;
} TimerEntryObj;

static PyTypeObject TimerEntry_Type;

static PyObject *
timerentry_cancel(TimerEntryObj *self, PyObject *Py_UNUSED(ignored))
{
    self->cancelled = 1;
    Py_CLEAR(self->target); /* release the callback/future eagerly */
    Py_RETURN_NONE;
}

static int
timerentry_traverse(TimerEntryObj *self, visitproc visit, void *arg)
{
    Py_VISIT(self->target);
    return 0;
}

static int
timerentry_clear(TimerEntryObj *self)
{
    Py_CLEAR(self->target);
    return 0;
}

static void
timerentry_dealloc(TimerEntryObj *self)
{
    PyObject_GC_UnTrack(self);
    Py_CLEAR(self->target);
    PyObject_GC_Del(self);
}

static PyMemberDef timerentry_members[] = {
    {"deadline_ns", Py_T_LONGLONG, offsetof(TimerEntryObj, deadline_ns), Py_READONLY, NULL},
    {"cancelled", Py_T_BOOL, offsetof(TimerEntryObj, cancelled), Py_READONLY, NULL},
    {NULL}
};

static PyMethodDef timerentry_methods[] = {
    {"cancel", (PyCFunction)timerentry_cancel, METH_NOARGS, NULL},
    {NULL}
};

static PyTypeObject TimerEntry_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_simloop.TimerEntry",
    .tp_basicsize = sizeof(TimerEntryObj),
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_dealloc = (destructor)timerentry_dealloc,
    .tp_traverse = (traverseproc)timerentry_traverse,
    .tp_clear = (inquiry)timerentry_clear,
    .tp_members = timerentry_members,
    .tp_methods = timerentry_methods,
    .tp_doc = "Cancelable handle to one timer registration.",
};

/* ------------------------------------------------------------------ Timers */

typedef struct {
    int64_t deadline;
    uint64_t seq;
    PyObject *target; /* strong: TimerEntryObj (kind 0) or SleepObj (kind 1) */
    uint64_t gen;     /* kind 1: must match the sleep's arm_gen to fire */
    char kind;
} HeapItem;

/* forward: kind-1 items check the sleep's generation */
static int heap_item_cancelled(const HeapItem *item);

typedef struct {
    PyObject_HEAD
    HeapItem *heap;
    Py_ssize_t size, cap;
    uint64_t next_seq;
    int64_t clock_ns;
    void *owner_loop; /* borrowed LoopObj*, see loop_init; may be NULL */
} TimersObj;

/* defined after LoopObj: flushes the loop's cached rng cursor before a
 * Python timer callback runs (callbacks may draw from the rng) */
static int loop_syncout_opaque(void *loop);

static PyTypeObject Timers_Type;

static inline int
heap_less(const HeapItem *a, const HeapItem *b)
{
    if (a->deadline != b->deadline)
        return a->deadline < b->deadline;
    return a->seq < b->seq;
}

static int
heap_reserve(TimersObj *t)
{
    if (t->size < t->cap)
        return 0;
    Py_ssize_t ncap = t->cap ? t->cap * 2 : 64;
    HeapItem *nh = (HeapItem *)PyMem_Realloc(t->heap, ncap * sizeof(HeapItem));
    if (nh == NULL) {
        PyErr_NoMemory();
        return -1;
    }
    t->heap = nh;
    t->cap = ncap;
    return 0;
}

static void
heap_sift_up(TimersObj *t, Py_ssize_t i)
{
    HeapItem item = t->heap[i];
    while (i > 0) {
        Py_ssize_t parent = (i - 1) >> 1;
        if (!heap_less(&item, &t->heap[parent]))
            break;
        t->heap[i] = t->heap[parent];
        i = parent;
    }
    t->heap[i] = item;
}

static void
heap_sift_down(TimersObj *t, Py_ssize_t i)
{
    HeapItem item = t->heap[i];
    Py_ssize_t n = t->size;
    for (;;) {
        Py_ssize_t child = 2 * i + 1;
        if (child >= n)
            break;
        if (child + 1 < n && heap_less(&t->heap[child + 1], &t->heap[child]))
            child += 1;
        if (!heap_less(&t->heap[child], &item))
            break;
        t->heap[i] = t->heap[child];
        i = child;
    }
    t->heap[i] = item;
}

/* pops the head; caller owns the reference in the returned item */
static HeapItem
heap_pop(TimersObj *t)
{
    HeapItem item = t->heap[0];
    t->size -= 1;
    if (t->size > 0) {
        t->heap[0] = t->heap[t->size];
        heap_sift_down(t, 0);
    }
    return item;
}

/* drop cancelled heads; returns 1 and sets *deadline if a live head exists */
static int
heap_live_head(TimersObj *t, int64_t *deadline)
{
    while (t->size > 0) {
        if (heap_item_cancelled(&t->heap[0])) {
            HeapItem item = heap_pop(t);
            Py_DECREF(item.target);
            continue;
        }
        *deadline = t->heap[0].deadline;
        return 1;
    }
    return 0;
}

static PyObject *
timers_push(TimersObj *self, PyObject *args)
{
    long long deadline;
    PyObject *target;
    if (!PyArg_ParseTuple(args, "LO", &deadline, &target))
        return NULL;
    TimerEntryObj *entry = PyObject_GC_New(TimerEntryObj, &TimerEntry_Type);
    if (entry == NULL)
        return NULL;
    entry->deadline_ns = deadline;
    entry->target = Py_NewRef(target);
    entry->cancelled = 0;
    PyObject_GC_Track((PyObject *)entry);
    if (heap_reserve(self) < 0) {
        Py_DECREF(entry);
        return NULL;
    }
    HeapItem *slot = &self->heap[self->size++];
    slot->deadline = deadline;
    slot->seq = ++self->next_seq; /* matches the Python pre-increment seq */
    slot->target = Py_NewRef((PyObject *)entry);
    slot->gen = 0;
    slot->kind = 0;
    heap_sift_up(self, self->size - 1);
    return (PyObject *)entry;
}

/* fire every entry due at the current clock; returns count or -1 */
static int
timers_fire_due_impl(TimersObj *self)
{
    int fired = 0;
    int64_t deadline;
    while (heap_live_head(self, &deadline) && deadline <= self->clock_ns) {
        HeapItem item = heap_pop(self);
        int rc;
        if (item.kind == 1) {
            /* direct sleep: resolving wakes tasks; Task.wake never draws
             * from the rng, so the loop's cached cursor stays valid */
            rc = future_resolve_none((FutureObj *)item.target);
            Py_DECREF(item.target);
        }
        else {
            TimerEntryObj *entry = (TimerEntryObj *)item.target;
            PyObject *target = entry->target;
            entry->target = NULL; /* transfer ownership */
            Py_DECREF(entry);
            if (target == NULL)
                continue; /* raced cancel */
            if (PyObject_TypeCheck(target, &Future_Type)) {
                rc = future_resolve_none((FutureObj *)target);
            }
            else {
                /* arbitrary Python callback: it may draw — flush the
                 * loop's cached rng cursor first */
                if (self->owner_loop != NULL &&
                    loop_syncout_opaque(self->owner_loop) < 0) {
                    Py_DECREF(target);
                    return -1;
                }
                PyObject *r = PyObject_CallNoArgs(target);
                rc = (r == NULL) ? -1 : 0;
                Py_XDECREF(r);
            }
            Py_DECREF(target);
        }
        if (rc < 0)
            return -1;
        fired += 1;
    }
    return fired;
}

static PyObject *
timers_fire_due(TimersObj *self, PyObject *Py_UNUSED(ignored))
{
    int n = timers_fire_due_impl(self);
    if (n < 0)
        return NULL;
    return PyLong_FromLong(n);
}

static PyObject *
timers_peek_deadline(TimersObj *self, PyObject *Py_UNUSED(ignored))
{
    int64_t deadline;
    if (!heap_live_head(self, &deadline))
        Py_RETURN_NONE;
    return PyLong_FromLongLong(deadline);
}

static PyObject *
timers_advance_ns(TimersObj *self, PyObject *arg)
{
    long long delta = PyLong_AsLongLong(arg);
    if (delta == -1 && PyErr_Occurred())
        return NULL;
    self->clock_ns += delta;
    if (self->size > 0 && self->heap[0].deadline <= self->clock_ns) {
        if (timers_fire_due_impl(self) < 0)
            return NULL;
    }
    Py_RETURN_NONE;
}

static PyObject *
timers_advance_to_next_event(TimersObj *self, PyObject *arg)
{
    long long epsilon = PyLong_AsLongLong(arg);
    if (epsilon == -1 && PyErr_Occurred())
        return NULL;
    int64_t deadline;
    if (!heap_live_head(self, &deadline))
        Py_RETURN_FALSE;
    int64_t jumped = deadline + epsilon;
    if (jumped > self->clock_ns)
        self->clock_ns = jumped;
    if (timers_fire_due_impl(self) < 0)
        return NULL;
    Py_RETURN_TRUE;
}

static Py_ssize_t
timers_len(TimersObj *self)
{
    return self->size;
}

static int
timers_traverse(TimersObj *self, visitproc visit, void *arg)
{
    for (Py_ssize_t i = 0; i < self->size; i++)
        Py_VISIT(self->heap[i].target);
    return 0;
}

static int
timers_clear_impl(TimersObj *self)
{
    Py_ssize_t n = self->size;
    self->size = 0;
    for (Py_ssize_t i = 0; i < n; i++)
        Py_CLEAR(self->heap[i].target);
    return 0;
}

static void
timers_dealloc(TimersObj *self)
{
    PyObject_GC_UnTrack(self);
    timers_clear_impl(self);
    PyMem_Free(self->heap);
    PyObject_GC_Del(self);
}

static PyObject *
timers_new(PyTypeObject *type, PyObject *args, PyObject *kwds)
{
    TimersObj *self = PyObject_GC_New(TimersObj, &Timers_Type);
    if (self == NULL)
        return NULL;
    self->heap = NULL;
    self->size = self->cap = 0;
    self->next_seq = 0;
    self->clock_ns = 0;
    self->owner_loop = NULL;
    PyObject_GC_Track((PyObject *)self);
    return (PyObject *)self;
}

static PyMemberDef timers_members[] = {
    {"clock", Py_T_LONGLONG, offsetof(TimersObj, clock_ns), 0, NULL},
    {NULL}
};

static PySequenceMethods timers_as_sequence = {
    .sq_length = (lenfunc)timers_len,
};

static PyMethodDef timers_methods[] = {
    {"push", (PyCFunction)timers_push, METH_VARARGS, NULL},
    {"fire_due", (PyCFunction)timers_fire_due, METH_NOARGS, NULL},
    {"peek_deadline", (PyCFunction)timers_peek_deadline, METH_NOARGS, NULL},
    {"advance_ns", (PyCFunction)timers_advance_ns, METH_O, NULL},
    {"advance_to_next_event", (PyCFunction)timers_advance_to_next_event, METH_O, NULL},
    {NULL}
};

static PyTypeObject Timers_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_simloop.Timers",
    .tp_basicsize = sizeof(TimersObj),
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_new = timers_new,
    .tp_dealloc = (destructor)timers_dealloc,
    .tp_traverse = (traverseproc)timers_traverse,
    .tp_clear = (inquiry)timers_clear_impl,
    .tp_members = timers_members,
    .tp_methods = timers_methods,
    .tp_as_sequence = &timers_as_sequence,
    .tp_doc = "Virtual clock + (deadline, seq)-ordered timer heap (C core).",
};

/* ------------------------------------------------------------------- Sleep */

typedef struct {
    FutureObj base;
    TimersObj *timers; /* strong */
    int64_t deadline_ns;
    uint64_t arm_gen;  /* bumped on reset; a queued heap item with a stale
                        * gen is dead (no TimerEntry object, no ref cycle) */
    char armed;
} SleepObj;

static int
heap_item_cancelled(const HeapItem *item)
{
    if (item->kind == 1)
        return ((SleepObj *)item->target)->arm_gen != item->gen;
    return ((TimerEntryObj *)item->target)->cancelled;
}

static int
sleep_arm(SleepObj *self)
{
    /* lazily register the timer — first-poll registration, matching the
     * Python Sleep (sleep.rs:30-44 waker semantics) */
    if (self->base.state != 0 || self->armed)
        return 0;
    if (self->deadline_ns <= self->timers->clock_ns)
        return future_resolve_none(&self->base);
    TimersObj *t = self->timers;
    if (heap_reserve(t) < 0)
        return -1;
    HeapItem *slot = &t->heap[t->size++];
    slot->deadline = self->deadline_ns;
    slot->seq = ++t->next_seq;
    slot->target = Py_NewRef((PyObject *)self);
    slot->gen = self->arm_gen;
    slot->kind = 1;
    heap_sift_up(t, t->size - 1);
    self->armed = 1;
    return 0;
}

static int
sleep_subscribe_impl(SleepObj *self, PyObject *task)
{
    if (sleep_arm(self) < 0)
        return -1;
    return future_subscribe_impl(&self->base, task);
}

static PyObject *
sleep_subscribe(SleepObj *self, PyObject *task)
{
    if (sleep_subscribe_impl(self, task) < 0)
        return NULL;
    Py_RETURN_NONE;
}

static PyObject *
sleep_reset(SleepObj *self, PyObject *deadline_obj)
{
    /* Sleep::reset (sleep.rs:47-55): move the deadline; if tasks are
     * already awaiting, re-arm immediately (they won't re-subscribe). */
    long long ns;
    if (PyLong_Check(deadline_obj)) {
        ns = PyLong_AsLongLong(deadline_obj);
    }
    else {
        PyObject *nso = PyObject_GetAttr(deadline_obj, s_ns); /* Instant */
        if (nso == NULL)
            return NULL;
        ns = PyLong_AsLongLong(nso);
        Py_DECREF(nso);
    }
    if (ns == -1 && PyErr_Occurred())
        return NULL;
    /* invalidate any queued registration (stale gen is skipped lazily) */
    self->arm_gen += 1;
    self->armed = 0;
    self->base.state = 0;
    Py_CLEAR(self->base.payload);
    self->deadline_ns = ns;
    if (self->base.wakers != NULL && PyList_GET_SIZE(self->base.wakers) > 0) {
        if (sleep_arm(self) < 0)
            return NULL;
    }
    Py_RETURN_NONE;
}

static PyObject *
sleep_is_elapsed(SleepObj *self, PyObject *Py_UNUSED(ignored))
{
    return PyBool_FromLong(self->base.state != 0);
}

static PyObject *
sleep_get_deadline(SleepObj *self, void *closure)
{
    if (instant_cls == NULL) {
        PyErr_SetString(PyExc_RuntimeError, "_simloop._configure was not called");
        return NULL;
    }
    PyObject *ns = PyLong_FromLongLong(self->deadline_ns);
    if (ns == NULL)
        return NULL;
    PyObject *r = PyObject_CallOneArg(instant_cls, ns);
    Py_DECREF(ns);
    return r;
}

static int
sleep_init(SleepObj *self, PyObject *args, PyObject *kwds)
{
    PyObject *timers;
    long long deadline;
    if (!PyArg_ParseTuple(args, "OL", &timers, &deadline))
        return -1;
    if (!PyObject_TypeCheck(timers, &Timers_Type)) {
        PyErr_SetString(PyExc_TypeError, "Sleep expects a _simloop.Timers core");
        return -1;
    }
    Py_XSETREF(self->timers, (TimersObj *)Py_NewRef(timers));
    self->deadline_ns = deadline;
    return 0;
}

static int
sleep_traverse(SleepObj *self, visitproc visit, void *arg)
{
    Py_VISIT((PyObject *)self->timers);
    return future_traverse(&self->base, visit, arg);
}

static int
sleep_clear(SleepObj *self)
{
    Py_CLEAR(self->timers);
    return future_clear(&self->base);
}

static void
sleep_dealloc(SleepObj *self)
{
    /* while armed the heap holds a strong ref, so dealloc implies the
     * sleep is not queued — nothing to cancel */
    PyObject_GC_UnTrack(self);
    sleep_clear(self);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyMethodDef sleep_methods[] = {
    {"subscribe", (PyCFunction)sleep_subscribe, METH_O, NULL},
    {"reset", (PyCFunction)sleep_reset, METH_O, NULL},
    {"is_elapsed", (PyCFunction)sleep_is_elapsed, METH_NOARGS, NULL},
    {NULL}
};

static PyGetSetDef sleep_getset[] = {
    {"deadline", (getter)sleep_get_deadline, NULL, NULL, NULL},
    {NULL}
};

static PyTypeObject Sleep_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_simloop.Sleep",
    .tp_basicsize = sizeof(SleepObj),
    .tp_base = &Future_Type,
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_BASETYPE | Py_TPFLAGS_HAVE_GC,
    .tp_new = PyType_GenericNew,
    .tp_init = (initproc)sleep_init,
    .tp_dealloc = (destructor)sleep_dealloc,
    .tp_traverse = (traverseproc)sleep_traverse,
    .tp_clear = (inquiry)sleep_clear,
    .tp_methods = sleep_methods,
    .tp_getset = sleep_getset,
    .tp_doc = "Future resolving when the virtual clock reaches the deadline (C core).",
};

/* -------------------------------------------------------------------- Loop */

typedef struct {
    PyObject_HEAD
    PyObject *executor;    /* madsim_tpu.task.Executor */
    PyObject *ready_items; /* the _PyReadyQueue._items list */
    PyObject *rng;         /* the GlobalRng */
    PyObject *rng_next;    /* bound GlobalRng.next_u64 (slow path) */
    TimersObj *timers;
    PyObject *tls;         /* madsim_tpu.context._tls */
    /* direct view of the rng's refill buffer.  Valid only between sync_in
     * and the next call into arbitrary Python (which may draw itself);
     * sync_out writes _buf_pos/_draw_count back before any such call. */
    PyObject *buf;         /* borrowed from rng._buf while valid */
    Py_ssize_t buf_pos;
    Py_ssize_t buf_len;
    long long draws;
    int rng_valid;         /* cached view is current */
    int rng_fast;          /* log/check off -> direct buffer reads allowed */
} LoopObj;

static PyTypeObject Loop_Type;

static PyObject *s__buf, *s__buf_pos, *s__draw_count, *s__log, *s__check;

/* write the cached cursor back onto the Python rng */
static int
loop_rng_sync_out(LoopObj *self)
{
    if (!self->rng_valid)
        return 0;
    self->rng_valid = 0;
    PyObject *pos = PyLong_FromSsize_t(self->buf_pos);
    if (pos == NULL)
        return -1;
    int rc = PyObject_SetAttr(self->rng, s__buf_pos, pos);
    Py_DECREF(pos);
    if (rc < 0)
        return -1;
    PyObject *draws = PyLong_FromLongLong(self->draws);
    if (draws == NULL)
        return -1;
    rc = PyObject_SetAttr(self->rng, s__draw_count, draws);
    Py_DECREF(draws);
    return rc;
}

static int
loop_rng_sync_in(LoopObj *self)
{
    PyObject *buf = PyObject_GetAttr(self->rng, s__buf);
    if (buf == NULL)
        return -1;
    if (!PyList_CheckExact(buf)) { /* None (not yet filled) or foreign type */
        Py_DECREF(buf);
        self->rng_valid = 0;
        self->buf = NULL;
        self->buf_pos = self->buf_len = 0;
        return 1; /* fall back to the Python call for this draw */
    }
    PyObject *pos = PyObject_GetAttr(self->rng, s__buf_pos);
    if (pos == NULL) {
        Py_DECREF(buf);
        return -1;
    }
    PyObject *draws = PyObject_GetAttr(self->rng, s__draw_count);
    if (draws == NULL) {
        Py_DECREF(buf);
        Py_DECREF(pos);
        return -1;
    }
    self->buf_pos = PyLong_AsSsize_t(pos);
    self->draws = PyLong_AsLongLong(draws);
    Py_DECREF(pos);
    Py_DECREF(draws);
    if (PyErr_Occurred()) {
        Py_DECREF(buf);
        return -1;
    }
    self->buf_len = PyList_GET_SIZE(buf);
    self->buf = buf; /* borrowed: rng._buf keeps it alive while valid */
    Py_DECREF(buf);
    self->rng_valid = 1;
    return 0;
}

static int
loop_rng_draw(LoopObj *self, uint64_t *out)
{
    if (self->rng_fast) {
        if (!self->rng_valid) {
            int rc = loop_rng_sync_in(self);
            if (rc < 0)
                return -1;
        }
        if (self->rng_valid && self->buf_pos < self->buf_len) {
            uint64_t v = PyLong_AsUnsignedLongLong(
                PyList_GET_ITEM(self->buf, self->buf_pos));
            if (v == (uint64_t)-1 && PyErr_Occurred())
                return -1;
            self->buf_pos += 1;
            self->draws += 1;
            *out = v;
            return 0;
        }
        /* exhausted or unfilled: let the Python refill path handle it */
        if (loop_rng_sync_out(self) < 0)
            return -1;
    }
    PyObject *vo = PyObject_CallNoArgs(self->rng_next);
    if (vo == NULL)
        return -1;
    uint64_t v = PyLong_AsUnsignedLongLong(vo);
    Py_DECREF(vo);
    if (v == (uint64_t)-1 && PyErr_Occurred())
        return -1;
    *out = v;
    return 0;
}

/* refresh the log/check gate.  Called once per drain iteration and again
 * before the per-poll advance draw, so enable_log()/enable_check() invoked
 * from INSIDE a task mid-drain takes effect from the very next draw (the
 * pure-Python next_u64 checks per draw; this keeps the native schedule's
 * determinism log byte-identical in that edge case).  Flipping fast->slow
 * hands the cached cursor back first so rng_next resumes at the right
 * buffer position. */
static int
loop_rng_gate(LoopObj *self)
{
    PyObject *log = PyObject_GetAttr(self->rng, s__log);
    if (log == NULL)
        return -1;
    PyObject *check = PyObject_GetAttr(self->rng, s__check);
    if (check == NULL) {
        Py_DECREF(log);
        return -1;
    }
    int fast = (log == Py_None && check == Py_None);
    Py_DECREF(log);
    Py_DECREF(check);
    if (!fast && self->rng_fast && loop_rng_sync_out(self) < 0)
        return -1;
    self->rng_fast = fast;
    return 0;
}

static inline int
attr_is_true(PyObject *obj, PyObject *name, int *out)
{
    PyObject *v = PyObject_GetAttr(obj, name);
    if (v == NULL)
        return -1;
    int t = PyObject_IsTrue(v);
    Py_DECREF(v);
    if (t < 0)
        return -1;
    *out = t;
    return 0;
}

static int
loop_syncout_opaque(void *loop)
{
    return loop_rng_sync_out((LoopObj *)loop);
}

static PyObject *
loop_run_all_ready(LoopObj *self, PyObject *Py_UNUSED(ignored))
{
    PyObject *items = self->ready_items;
    TimersObj *timers = self->timers;
    PyObject *tls = self->tls;

    for (;;) {
        /* re-gate each iteration: the previous iteration may have run task
         * code (poll, drop finally-blocks) that toggled log/check */
        if (loop_rng_gate(self) < 0)
            return NULL;
        Py_ssize_t n = PyList_GET_SIZE(items);
        if (n == 0)
            break;

        /* random swap-remove pop: same Lemire draw as the Python path */
        uint64_t v;
        if (loop_rng_draw(self, &v) < 0)
            return NULL;
        Py_ssize_t idx = (Py_ssize_t)(((unsigned __int128)v * (uint64_t)n) >> 64);

        PyObject *task = Py_NewRef(PyList_GET_ITEM(items, idx));
        PyList_SetItem(items, idx, Py_NewRef(PyList_GET_ITEM(items, n - 1)));
        if (PyList_SetSlice(items, n - 1, n, NULL) < 0) {
            Py_DECREF(task);
            return NULL;
        }

        if (PyObject_SetAttr(task, s_scheduled, Py_False) < 0) {
            Py_DECREF(task);
            return NULL;
        }
        int flag;
        if (attr_is_true(task, s_finished, &flag) < 0) {
            Py_DECREF(task);
            return NULL;
        }
        if (flag) {
            Py_DECREF(task);
            continue;
        }
        PyObject *node = PyObject_GetAttr(task, s_node);
        if (node == NULL) {
            Py_DECREF(task);
            return NULL;
        }
        int cancelled, killed;
        if (attr_is_true(task, s_cancelled, &cancelled) < 0 ||
            attr_is_true(node, s_killed, &killed) < 0) {
            Py_DECREF(node);
            Py_DECREF(task);
            return NULL;
        }
        if (cancelled || killed) {
            /* coro.close() runs finally blocks, which may draw */
            if (loop_rng_sync_out(self) < 0) {
                Py_DECREF(node);
                Py_DECREF(task);
                return NULL;
            }
            PyObject *r = PyObject_CallMethodObjArgs(
                self->executor, s__drop_task, task, NULL);
            Py_DECREF(node);
            Py_DECREF(task);
            if (r == NULL)
                return NULL;
            Py_DECREF(r);
            continue;
        }
        int paused;
        if (attr_is_true(node, s_paused, &paused) < 0) {
            Py_DECREF(node);
            Py_DECREF(task);
            return NULL;
        }
        if (paused) {
            /* park until resume (ref task/mod.rs:271-276) */
            PyObject *pt = PyObject_GetAttr(node, s_paused_tasks);
            Py_DECREF(node);
            if (pt == NULL) {
                Py_DECREF(task);
                return NULL;
            }
            int rc;
            if (PyList_Check(pt)) {
                rc = PyList_Append(pt, task);
            }
            else {
                rc = loop_rng_sync_out(self);
                if (rc == 0) {
                    PyObject *r = PyObject_CallMethod(pt, "append", "O", task);
                    rc = (r == NULL) ? -1 : 0;
                    Py_XDECREF(r);
                }
            }
            Py_DECREF(pt);
            Py_DECREF(task);
            if (rc < 0)
                return NULL;
            continue;
        }
        Py_DECREF(node);

        /* ---- poll: step the coroutine inside the task context ---- */
        PyObject *coro = PyObject_GetAttr(task, s_coro);
        if (coro == NULL) {
            Py_DECREF(task);
            return NULL;
        }
        PyObject *prev = PyObject_GetAttr(tls, s_task);
        if (prev == NULL) {
            PyErr_Clear();
            prev = Py_NewRef(Py_None);
        }
        if (PyObject_SetAttr(tls, s_task, task) < 0) {
            Py_DECREF(prev);
            Py_DECREF(coro);
            Py_DECREF(task);
            return NULL;
        }
        /* the coroutine body may draw from the rng */
        if (loop_rng_sync_out(self) < 0) {
            Py_DECREF(prev);
            Py_DECREF(coro);
            Py_DECREF(task);
            return NULL;
        }
        PyObject *pollable = NULL;
        PySendResult sr = PyIter_Send(coro, Py_None, &pollable);
        Py_DECREF(coro);
        /* restore context before completion/panic handling, matching the
         * Python finally */
        if (PyObject_SetAttr(tls, s_task, prev) < 0) {
            Py_DECREF(prev);
            Py_XDECREF(pollable);
            Py_DECREF(task);
            return NULL;
        }
        Py_DECREF(prev);

        if (sr == PYGEN_RETURN) {
            /* cursor is already flushed (sync_out precedes every send) and
             * the coroutine may have drawn, so the cache is stale — it
             * re-syncs on the next draw */
            PyObject *r = PyObject_CallMethodObjArgs(
                self->executor, s__complete, task, pollable, NULL);
            Py_DECREF(pollable);
            Py_DECREF(task);
            if (r == NULL)
                return NULL;
            Py_DECREF(r);
        }
        else if (sr == PYGEN_ERROR) {
            PyObject *exc = PyErr_GetRaisedException();
            PyObject *handled = PyObject_CallMethodObjArgs(
                self->executor, s__poll_raised, task, exc, NULL);
            if (handled == NULL) {
                Py_DECREF(exc);
                Py_DECREF(task);
                return NULL;
            }
            int h = PyObject_IsTrue(handled);
            Py_DECREF(handled);
            if (h <= 0) {
                /* not handled (KeyboardInterrupt etc.): propagate */
                PyErr_SetRaisedException(exc);
                Py_DECREF(task);
                return NULL;
            }
            Py_DECREF(exc);
            Py_DECREF(task);
        }
        else {
            /* subscribe the yielded pollable; C fast path for the exact
             * core types, generic dispatch otherwise */
            int rc;
            PyTypeObject *pt = Py_TYPE(pollable);
            if (pt == &Sleep_Type)
                rc = sleep_subscribe_impl((SleepObj *)pollable, task);
            else if (pt == &Future_Type)
                rc = future_subscribe_impl((FutureObj *)pollable, task);
            else {
                /* arbitrary subscribe may draw (netsim pollables) */
                rc = loop_rng_sync_out(self);
                if (rc == 0) {
                    PyObject *r = PyObject_CallMethodObjArgs(
                        pollable, s_subscribe, task, NULL);
                    rc = (r == NULL) ? -1 : 0;
                    Py_XDECREF(r);
                }
            }
            Py_DECREF(pollable);
            Py_DECREF(task);
            if (rc < 0)
                return NULL;
        }

        /* random 50-100 ns advance per poll (ref task/mod.rs:312-315);
         * the poll above ran task code, so re-gate before drawing */
        if (loop_rng_gate(self) < 0)
            return NULL;
        if (loop_rng_draw(self, &v) < 0)
            return NULL;
        timers->clock_ns += 50 + (int64_t)(((unsigned __int128)v * 51) >> 64);
        if (timers->size > 0 && timers->heap[0].deadline <= timers->clock_ns) {
            if (timers_fire_due_impl(timers) < 0)
                return NULL;
        }
    }
    /* hand the cursor back before returning to Python */
    if (loop_rng_sync_out(self) < 0)
        return NULL;
    Py_RETURN_NONE;
}

static PyObject *
loop_run(LoopObj *self, PyObject *args)
{
    /* the block_on inner loop (ref task/mod.rs:220-260): drain ready,
     * check main, jump to the next timer.  The time limit is RE-READ from
     * the executor each iteration (not snapshotted) so a mid-sim
     * set_time_limit behaves identically to the Python loop. */
    PyObject *main_join;        /* a Future (JoinHandle) */
    PyObject *deadlock_exc;     /* exception CLASS for deadlock */
    long long epsilon = 50;
    if (!PyArg_ParseTuple(args, "OO|L", &main_join, &deadlock_exc, &epsilon))
        return NULL;
    if (!PyObject_TypeCheck(main_join, &Future_Type)) {
        PyErr_SetString(PyExc_TypeError, "main_join must be a Future");
        return NULL;
    }
    FutureObj *main_fut = (FutureObj *)main_join;
    TimersObj *timers = self->timers;
    for (;;) {
        PyObject *r = loop_run_all_ready(self, NULL);
        if (r == NULL)
            return NULL;
        Py_DECREF(r);
        if (main_fut->state == 1)
            return Py_NewRef(main_fut->payload);
        if (main_fut->state == 2) {
            PyErr_SetRaisedException(Py_NewRef(main_fut->payload));
            return NULL;
        }
        int64_t deadline;
        if (!heap_live_head(timers, &deadline)) {
            PyErr_SetString(deadlock_exc,
                "deadlock detected: no timers are pending and every task "
                "is blocked — the simulation can never make progress");
            return NULL;
        }
        int64_t jumped = deadline + epsilon;
        if (jumped > timers->clock_ns)
            timers->clock_ns = jumped;
        if (timers_fire_due_impl(timers) < 0)
            return NULL;
        PyObject *limit = PyObject_GetAttr(self->executor, s_time_limit_ns);
        if (limit == NULL)
            return NULL;
        if (limit != Py_None) {
            long long lim = PyLong_AsLongLong(limit);
            Py_DECREF(limit);
            if (lim == -1 && PyErr_Occurred())
                return NULL;
            if (timers->clock_ns > lim) {
                /* the helper raises TimeLimitError with the formatted
                 * message the Python loop produces */
                PyObject *r = PyObject_CallMethodNoArgs(
                    self->executor, s__raise_time_limit);
                if (r != NULL) { /* helper must raise */
                    Py_DECREF(r);
                    PyErr_SetString(PyExc_RuntimeError,
                                    "_raise_time_limit did not raise");
                }
                return NULL;
            }
        }
        else {
            Py_DECREF(limit);
        }
    }
}

static int
loop_init(LoopObj *self, PyObject *args, PyObject *kwds)
{
    PyObject *executor, *ready_items, *rng, *timers, *tls;
    if (!PyArg_ParseTuple(args, "OOOOO", &executor, &ready_items, &rng,
                          &timers, &tls))
        return -1;
    if (!PyList_Check(ready_items)) {
        PyErr_SetString(PyExc_TypeError, "ready_items must be a list");
        return -1;
    }
    if (!PyObject_TypeCheck(timers, &Timers_Type)) {
        PyErr_SetString(PyExc_TypeError, "timers must be a _simloop.Timers");
        return -1;
    }
    PyObject *rng_next = PyObject_GetAttrString(rng, "next_u64");
    if (rng_next == NULL)
        return -1;
    Py_XSETREF(self->executor, Py_NewRef(executor));
    Py_XSETREF(self->ready_items, Py_NewRef(ready_items));
    Py_XSETREF(self->rng, Py_NewRef(rng));
    Py_XSETREF(self->rng_next, rng_next);
    Py_XSETREF(self->timers, (TimersObj *)Py_NewRef(timers));
    Py_XSETREF(self->tls, Py_NewRef(tls));
    self->buf = NULL;
    self->buf_pos = self->buf_len = 0;
    self->draws = 0;
    self->rng_valid = 0;
    self->rng_fast = 0;
    /* let timer callbacks flush our cached rng cursor */
    self->timers->owner_loop = (void *)self;
    return 0;
}

static int
loop_traverse(LoopObj *self, visitproc visit, void *arg)
{
    Py_VISIT(self->executor);
    Py_VISIT(self->ready_items);
    Py_VISIT(self->rng);
    Py_VISIT(self->rng_next);
    Py_VISIT((PyObject *)self->timers);
    Py_VISIT(self->tls);
    return 0;
}

static int
loop_clear(LoopObj *self)
{
    if (self->timers != NULL && self->timers->owner_loop == (void *)self)
        self->timers->owner_loop = NULL;
    Py_CLEAR(self->executor);
    Py_CLEAR(self->ready_items);
    Py_CLEAR(self->rng);
    Py_CLEAR(self->rng_next);
    Py_CLEAR(self->timers);
    Py_CLEAR(self->tls);
    self->buf = NULL;
    self->rng_valid = 0;
    return 0;
}

static void
loop_dealloc(LoopObj *self)
{
    PyObject_GC_UnTrack(self);
    loop_clear(self);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyMethodDef loop_methods[] = {
    {"run_all_ready", (PyCFunction)loop_run_all_ready, METH_NOARGS, NULL},
    {"run", (PyCFunction)loop_run, METH_VARARGS, NULL},
    {NULL}
};

static PyTypeObject Loop_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_simloop.Loop",
    .tp_basicsize = sizeof(LoopObj),
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_new = PyType_GenericNew,
    .tp_init = (initproc)loop_init,
    .tp_dealloc = (destructor)loop_dealloc,
    .tp_traverse = (traverseproc)loop_traverse,
    .tp_clear = (inquiry)loop_clear,
    .tp_methods = loop_methods,
    .tp_doc = "The executor's compiled ready-loop driver.",
};

/* ------------------------------------------------------------------ module */

static PyObject *
mod_configure(PyObject *module, PyObject *arg)
{
    /* time.py hands us its Instant class for Sleep.deadline */
    Py_XSETREF(instant_cls, Py_NewRef(arg));
    Py_RETURN_NONE;
}

static PyMethodDef module_methods[] = {
    {"_configure", (PyCFunction)mod_configure, METH_O, NULL},
    {NULL}
};

static struct PyModuleDef simloop_module = {
    PyModuleDef_HEAD_INIT,
    .m_name = "_simloop",
    .m_doc = "Compiled executor core (ready loop, timers, futures) for the host tier.",
    .m_size = -1,
    .m_methods = module_methods,
};

PyMODINIT_FUNC
PyInit__simloop(void)
{
    s_wake = PyUnicode_InternFromString("wake");
    s_subscribe = PyUnicode_InternFromString("subscribe");
    s_scheduled = PyUnicode_InternFromString("scheduled");
    s_finished = PyUnicode_InternFromString("finished");
    s_cancelled = PyUnicode_InternFromString("cancelled");
    s_node = PyUnicode_InternFromString("node");
    s_killed = PyUnicode_InternFromString("killed");
    s_paused = PyUnicode_InternFromString("paused");
    s_paused_tasks = PyUnicode_InternFromString("paused_tasks");
    s_coro = PyUnicode_InternFromString("coro");
    s_task = PyUnicode_InternFromString("task");
    s__drop_task = PyUnicode_InternFromString("_drop_task");
    s__complete = PyUnicode_InternFromString("_complete");
    s__poll_raised = PyUnicode_InternFromString("_poll_raised");
    s_ns = PyUnicode_InternFromString("ns");
    s__buf = PyUnicode_InternFromString("_buf");
    s__buf_pos = PyUnicode_InternFromString("_buf_pos");
    s__draw_count = PyUnicode_InternFromString("_draw_count");
    s__log = PyUnicode_InternFromString("_log");
    s__check = PyUnicode_InternFromString("_check");
    s__ready_items = PyUnicode_InternFromString("_ready_items");
    s_time_limit_ns = PyUnicode_InternFromString("time_limit_ns");
    s__raise_time_limit = PyUnicode_InternFromString("_raise_time_limit");

    if (PyType_Ready(&Future_Type) < 0 ||
        PyType_Ready(&TimerEntry_Type) < 0 || PyType_Ready(&Timers_Type) < 0 ||
        PyType_Ready(&Sleep_Type) < 0 || PyType_Ready(&Loop_Type) < 0)
        return NULL;

    PyObject *m = PyModule_Create(&simloop_module);
    if (m == NULL)
        return NULL;
    if (PyModule_AddObjectRef(m, "Future", (PyObject *)&Future_Type) < 0 ||
        PyModule_AddObjectRef(m, "Sleep", (PyObject *)&Sleep_Type) < 0 ||
        PyModule_AddObjectRef(m, "TimerEntry", (PyObject *)&TimerEntry_Type) < 0 ||
        PyModule_AddObjectRef(m, "Timers", (PyObject *)&Timers_Type) < 0 ||
        PyModule_AddObjectRef(m, "Loop", (PyObject *)&Loop_Type) < 0) {
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
