"""Raft (election + log replication) as a device workload — the MadRaft
sweep.

This is the flagship model for the engine: an N-node Raft cluster — leader
election with the §5.4.1 vote restriction, single-entry AppendEntries
replication with consistency checks and next/match-index bookkeeping, and
commit advancement under the §5.4.2 current-term rule (Ongaro & Ousterhout)
— with crash/restart fault injection and per-message loss/latency,
expressed as pure array handlers so thousands of seeds run in lockstep on
TPU. It plays the role the MadRaft test suite plays for the reference
(BASELINE.md configs #3/#5): randomized schedules + faults hunting for
safety violations, with every found seed replayable bit-exactly on CPU via
``engine.run_traced``.

Two safety invariants are checked online, any breach latches ``violation``:
- **election safety**: at most one leader per term (a (term, winner) ring
  compared on every won election);
- **log matching at commit**: the first node to commit index i records
  the entry term; every later commit of i must agree.

Mechanics mirrored from the reference simulator rather than any Raft
implementation: message delivery = link test + latency draw
(madsim/src/sim/net/network.rs:261-269), node crash/restart semantics =
kill/restart with durable (term, vote, log) vs volatile (role, votes,
commit) state (madsim/src/sim/task/mod.rs:347-394), randomized timers =
the virtual-clock timer queue (madsim/src/sim/time/mod.rs:142-153).

Design notes:
- Timer staleness uses generation counters (``tgen`` per node for election
  timers, ``lepoch`` for heartbeat timers) instead of cancellation — the
  queue is append-only, cancellation is a pay-mismatch drop.
- Replication ships ONE entry per AppendEntries (the follower's
  next-index entry), so message payloads stay fixed-width; heartbeats are
  empty appends. Leaders retry/decrement on rejection — the classic loop.
- Logs are bounded arrays (``log_cap`` entries); a seed whose log would
  overflow latches ``log_overflow`` and stops appending (surfaced in the
  sweep summary, never silent).
- All node/log indexing is one-hot masked (engine/ops.py): under vmap,
  dynamic scatter/gather lower to TPU ops ~6-10x slower than the dense
  masked equivalents, and the handlers run for every seed every step.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

from ..engine import faults as efaults
from ..engine import net as enet
from ..engine.core import Emits, EngineConfig, Workload
from ..engine.ops import get1, get2, geti, set1, set2
from ..engine.rng import bounded, prob_to_q32
from ..oracle.history import OP_ELECT, PH_INVOKE
from . import _common

# event kinds
K_ELECTION = 0  # pay = (node, tgen)
K_HEARTBEAT = 1  # pay = (node, lepoch)
K_MSG = 2  # pay = (dst, mtype, src, term, a, b, c, d)
K_FAULT = 3  # pay = (action, victim, t_lo, t_hi) — engine/faults.py stream
K_CMD = 4  # pay = (target, retries) — a client command seeking the leader

# message types
M_REQ_VOTE = 0  # a=last_log_idx, b=last_log_term
M_VOTE_GRANT = 1
M_APPEND = 2  # a=prev_idx, b=prev_term, c=entry_term (0 = heartbeat), d=commit
M_APPEND_RSP = 3  # a=success, b=match_idx

# roles
FOLLOWER = 0
CANDIDATE = 1
LEADER = 2

PAYLOAD_SLOTS = 8

# violation flavors (bitmask latched in ``viol_kind``; ``violation`` stays
# the any-flavor bool). The explore subsystem's triage keys on these.
V_ELECTION = 1  # two leaders elected in one term
V_COMMIT = 2  # log-matching breach at commit

N_KINDS = 5  # event kinds above
N_ROLE_TRANS = 9  # role_before * 3 + role_after


class RaftConfig(NamedTuple):
    """Static sweep parameters (hashable — part of the jit key)."""

    num_nodes: int = 5
    election_lo_ns: int = 150_000_000
    election_hi_ns: int = 300_000_000
    heartbeat_ns: int = 50_000_000
    # client command plan: `commands` K_CMD events in the first
    # `cmd_window_ns`, retrying every retry_ns until a leader accepts
    commands: int = 8
    cmd_window_ns: int = 4_000_000_000
    cmd_retry_ns: int = 50_000_000
    # a command that can't find a leader stops retrying after this many
    # attempts (surfaced as cmd_giveups) instead of spinning K_CMD events
    # until the time limit in partitioned seeds
    cmd_max_retries: int = 64
    log_cap: int = 32
    # legacy crash-storm shorthand, compiled through engine/faults.py;
    # `faults` (below) overrides all four when set
    crashes: int = 2
    crash_window_ns: int = 5_000_000_000
    restart_lo_ns: int = 100_000_000
    restart_hi_ns: int = 1_000_000_000
    # network model (reference defaults: 1-10 ms latency, lossless)
    loss_q32: int = prob_to_q32(0.01)
    lat_lo_ns: int = 1_000_000
    lat_hi_ns: int = 10_000_000
    # buggified latency spikes (ref net/mod.rs:287-295: 10% → 1-5 s when
    # buggify is enabled); 0 disables
    buggify_q32: int = 0
    history: int = 16  # election-safety ring size
    # model the host-tier example's amnesia bug: crash wipes DURABLE state
    # too (term/voted/log), so a restarted node can re-vote in a term it
    # already voted in — the election-safety checker catches the double
    # vote. Used by the cross-tier replay pipeline (madsim_tpu/replay.py)
    # to find device seeds whose fault schedule breaks host-tier user code.
    volatile_state: bool = False
    # operation-history buffer rows per seed (madsim_tpu/oracle); 0 =
    # recording off. Raft records one OP_ELECT invoke row per won
    # election (client = winner node, key = term) — the history the
    # differential harness checks against oracle.specs.ElectionSpec on
    # both tiers (explore/differential.py).
    hist_slots: int = 0
    # full declarative fault campaign (engine/faults.FaultSpec), a
    # literal schedule, or a FaultEnvelope — the spec-as-data path, where
    # the jit key is the envelope SHAPE and the concrete candidate rides
    # in as per-lane FaultParams (run_sweep's ``params=``); None =
    # derive a crash-storm spec from the legacy fields above
    faults: Optional[
        Union[efaults.FaultSpec, efaults.FixedFaults, efaults.FaultEnvelope]
    ] = None
    # opt-in device-side event-mix telemetry plane (madsim_tpu/obs):
    # per-seed uint32 counters, one per event kind (N_KINDS), summarized
    # into the chunk's ``event_mix`` histogram. Changes the summary
    # schema and the checkpoint fingerprint — off by default so stock
    # sweeps stay byte-identical.
    event_mix: bool = False


def fault_spec(cfg: RaftConfig) -> efaults.FaultSpec:
    """The campaign this config compiles: ``cfg.faults`` verbatim, or the
    legacy crash-storm fields lifted into a FaultSpec."""
    if cfg.faults is not None:
        return cfg.faults
    return efaults.FaultSpec(
        crashes=cfg.crashes,
        crash_window_ns=cfg.crash_window_ns,
        restart_lo_ns=cfg.restart_lo_ns,
        restart_hi_ns=cfg.restart_hi_ns,
    )


def _rt(cfg: RaftConfig, w: "RaftState"):
    """Runtime spec view for the in-loop interpreter: the static spec on
    the legacy path, this lane's traced ``FaultRt`` on the envelope path."""
    return efaults.runtime_spec(fault_spec(cfg), w.frt)


def _shadow_nodes(cfg: RaftConfig) -> int:
    """Width of the durability-shadow planes: ``num_nodes`` iff the
    (static, jit-cache-key) spec can open a slow-disk window. Without
    fsync stalls the shadow provably equals the live durable state after
    every event, so the planes go width-0 and every shadow write and
    crash rollback is gated off at trace time — the no-stall common case
    (all pre-gray configs, the headline benchmarks) pays nothing. A
    ``FaultEnvelope`` decides this once per CAMPAIGN (any candidate it
    covers could draw a stall window), not per candidate."""
    return cfg.num_nodes if efaults.can_stall(fault_spec(cfg)) else 0


class RaftState(NamedTuple):
    # per-node Raft state [N] (term/voted/log are durable across crashes)
    role: jnp.ndarray  # int32
    term: jnp.ndarray  # int32
    voted: jnp.ndarray  # int32, -1 = none
    votes: jnp.ndarray  # uint32 bitmask of granted votes
    fstate: efaults.FaultState  # shared liveness/pause/partition/burst state
    last_hb: jnp.ndarray  # int64, last time a valid leader signal arrived
    tgen: jnp.ndarray  # int32 election-timer generation
    lepoch: jnp.ndarray  # int32 leadership epoch (heartbeat-timer guard)
    # replicated log [N, L]: term of each entry; slot 0 is the sentinel
    log_term: jnp.ndarray  # int32[N, L]
    log_len: jnp.ndarray  # int32[N] (== last used index; entries 1..len)
    # durability plane (gray failures, docs/faults.md): the SYNCED shadow
    # of the durable state. Outside slow-disk windows the shadow tracks
    # the live values event by event (fsync-on-mutate, the correct-raft
    # discipline); inside a window it freezes, and a crash/power_fail
    # rolls the live values back to it — crash-without-sync as a
    # schedulable fault. The model acks before fsync completes (the
    # realistic bug class), so stall+power_fail campaigns CAN surface
    # genuine election/commit-safety violations. All four planes are
    # width-0 (and the writes statically gated off) when the spec draws
    # no fsync-stall windows — see ``_shadow_nodes``.
    dur_term: jnp.ndarray  # int32[SN]  (SN = num_nodes or 0)
    dur_voted: jnp.ndarray  # int32[SN]
    dur_log_term: jnp.ndarray  # int32[SN, L]
    dur_log_len: jnp.ndarray  # int32[SN]
    commit: jnp.ndarray  # int32[N] (volatile)
    next_idx: jnp.ndarray  # int32[N, N] (leader bookkeeping, volatile)
    match_idx: jnp.ndarray  # int32[N, N]
    # network
    links: enet.LinkState
    # election-safety ring [H]
    hist_term: jnp.ndarray  # int32
    hist_node: jnp.ndarray  # int32
    hist_valid: jnp.ndarray  # bool
    hist_pos: jnp.ndarray  # int32
    # log-matching-at-commit checker [L]
    chist_term: jnp.ndarray  # int32
    chist_set: jnp.ndarray  # bool
    # sweep outputs
    violation: jnp.ndarray  # bool (any flavor)
    viol_kind: jnp.ndarray  # int32 flavor bitmask (V_ELECTION | V_COMMIT)
    log_overflow: jnp.ndarray  # bool
    elections: jnp.ndarray  # int32
    commits: jnp.ndarray  # int32 (total commit-index advancement)
    accepted_cmds: jnp.ndarray  # int32
    cmd_giveups: jnp.ndarray  # int32 commands that hit the retry cap
    msgs_sent: jnp.ndarray  # int32
    msgs_delivered: jnp.ndarray  # int32
    # spec-as-data (engine/faults.py): this lane's runtime override
    # scalars (FaultRt) on the envelope path; an empty, leafless () on
    # the legacy path — zero loop-carry cost there
    frt: object


def _pay(*vals) -> jnp.ndarray:
    return _common.pay(*vals, slots=PAYLOAD_SLOTS)


_DISABLED_EXTRA = _common.DISABLED  # sentinel: an unused extra slot


def _emits(cfg: RaftConfig, bcast, *extras) -> Emits:
    return _common.pack_emits(PAYLOAD_SLOTS, bcast, *extras)


def _no_bcast(cfg: RaftConfig):
    return _common.no_bcast(cfg.num_nodes, PAYLOAD_SLOTS, K_MSG)


def _pays(cfg: RaftConfig, mtype, src, term, a=0, b=0, c=0, d=0) -> jnp.ndarray:
    """[N, P] message payloads addressed to every node; each field is a
    scalar (broadcast) or an [N] vector (per-destination)."""
    n = cfg.num_nodes
    dst = jnp.arange(n, dtype=jnp.int32)

    def col(v):
        return jnp.broadcast_to(jnp.asarray(v, jnp.int32), (n,))

    cols = [dst, col(mtype), col(src), col(term), col(a), col(b), col(c), col(d)]
    return jnp.stack(cols, axis=1)


def _broadcast(cfg: RaftConfig, w: RaftState, now, src, rand, enable, pays):
    """Emit slots 0..N-1: one message per destination (self slot disabled),
    each individually link-tested — all vectorized, no per-node loop."""
    n = cfg.num_nodes
    u = rand[: 2 * n].reshape(n, 2)
    times, deliver = enet.route_from(w.links, now, src, u[:, 0], u[:, 1])
    enables = enable & (jnp.arange(n, dtype=jnp.int32) != src) & deliver
    kinds = jnp.full((n,), K_MSG, jnp.int32)
    sent = jnp.where(enable, jnp.int32(cfg.num_nodes - 1), 0)
    delivered = jnp.sum(enables, dtype=jnp.int32)
    return (times, kinds, pays, enables), sent, delivered


def _record_election(cfg: RaftConfig, w: RaftState, term, node, won):
    """Online election-safety check: a term may elect at most one leader."""
    dup = jnp.any(w.hist_valid & (w.hist_term == term) & (w.hist_node != node))
    slot = w.hist_pos % cfg.history
    return w._replace(
        violation=w.violation | (won & dup),
        viol_kind=w.viol_kind
        | jnp.where(won & dup, jnp.int32(V_ELECTION), jnp.int32(0)),
        hist_term=set1(w.hist_term, slot, term, won),
        hist_node=set1(w.hist_node, slot, node, won),
        hist_valid=set1(w.hist_valid, slot, True, won),
        hist_pos=jnp.where(won, w.hist_pos + 1, w.hist_pos),
        elections=jnp.where(won, w.elections + 1, w.elections),
    )


def _advance_commit(cfg: RaftConfig, w: RaftState, node, new_commit, enable):
    """Move ``commit[node]`` to ``new_commit`` and run the log-matching
    checker over the newly committed range."""
    old = get1(w.commit, node)
    new = jnp.where(enable, jnp.maximum(old, new_commit.astype(jnp.int32)), old)
    idx = jnp.arange(cfg.log_cap, dtype=jnp.int32)
    fresh = (idx > old) & (idx <= new)
    my_terms = get1(w.log_term, node)
    mismatch = jnp.any(fresh & w.chist_set & (w.chist_term != my_terms))
    return w._replace(
        commit=set1(w.commit, node, new),
        chist_term=jnp.where(fresh & ~w.chist_set, my_terms, w.chist_term),
        chist_set=w.chist_set | fresh,
        violation=w.violation | mismatch,
        viol_kind=w.viol_kind
        | jnp.where(mismatch, jnp.int32(V_COMMIT), jnp.int32(0)),
        commits=w.commits + (new - old).astype(jnp.int32),
    )


def _append_pays(cfg: RaftConfig, w: RaftState, leader, term) -> jnp.ndarray:
    """AppendEntries payloads [N, P]: each follower gets the entry at its
    next-index (or a pure heartbeat when the log has nothing newer)."""
    nxt = get1(w.next_idx, leader)  # [N]
    log_row = get1(w.log_term, leader)  # [L]
    prev_idx = nxt - 1
    prev_term = geti(log_row, prev_idx)  # [N]
    has_entry = nxt <= get1(w.log_len, leader)
    safe_nxt = jnp.minimum(nxt, cfg.log_cap - 1)
    ent_term = jnp.where(has_entry, geti(log_row, safe_nxt), 0)
    return _pays(
        cfg, M_APPEND, leader, term, prev_idx, prev_term, ent_term,
        get1(w.commit, leader),
    )


# -- event handlers (each: (w, now, pay, rand) -> (w, Emits)) ---------------


def _on_election_timer(cfg: RaftConfig, w: RaftState, now, pay, rand):
    node, gen = pay[0], pay[1]
    valid = get1(efaults.up(w.fstate), node) & (gen == get1(w.tgen, node)) & (
        get1(w.role, node) != LEADER
    )
    # a live leader/candidate signal arrived since this timer was armed?
    recent = (get1(w.last_hb, node) + cfg.election_lo_ns) > now
    starting = valid & ~recent

    new_term = get1(w.term, node) + 1
    self_bit = jnp.left_shift(jnp.uint32(1), node.astype(jnp.uint32))
    w2 = w._replace(
        term=set1(w.term, node, new_term, starting),
        role=set1(w.role, node, CANDIDATE, starting),
        voted=set1(w.voted, node, node, starting),
        votes=set1(w.votes, node, self_bit, starting),
        last_hb=set1(w.last_hb, node, now, starting),
    )
    last_idx = get1(w.log_len, node)
    last_term = get2(w.log_term, node, last_idx)
    bcast, sent, delivered = _broadcast(
        cfg, w2, now, node, rand, starting,
        _pays(cfg, M_REQ_VOTE, node, new_term, last_idx, last_term),
    )
    # timer arming runs on the node's own (possibly skewed) clock
    timeout = efaults.skewed_delay(
        fault_spec(cfg), w.fstate, node,
        bounded(rand[2 * cfg.num_nodes], cfg.election_lo_ns, cfg.election_hi_ns),
        rt=_rt(cfg, w),
    )
    emits = _emits(
        cfg,
        bcast,
        # one live election timer per node, always re-armed while valid
        (now + timeout, K_ELECTION, _pay(node, get1(w.tgen, node)), valid),
        _DISABLED_EXTRA,
    )
    w2 = w2._replace(msgs_sent=w2.msgs_sent + sent, msgs_delivered=w2.msgs_delivered + delivered)
    return w2, emits


def _on_heartbeat_timer(cfg: RaftConfig, w: RaftState, now, pay, rand):
    node, epoch = pay[0], pay[1]
    valid = get1(efaults.up(w.fstate), node) & (get1(w.role, node) == LEADER) & (
        epoch == get1(w.lepoch, node)
    )
    term = get1(w.term, node)
    bcast, sent, delivered = _broadcast(
        cfg, w, now, node, rand, valid, _append_pays(cfg, w, node, term)
    )
    hb = efaults.skewed_delay(
        fault_spec(cfg), w.fstate, node, cfg.heartbeat_ns, rt=_rt(cfg, w)
    )
    emits = _emits(
        cfg,
        bcast,
        (now + hb, K_HEARTBEAT, _pay(node, epoch), valid),
        _DISABLED_EXTRA,
    )
    w2 = w._replace(msgs_sent=w.msgs_sent + sent, msgs_delivered=w.msgs_delivered + delivered)
    return w2, emits


def _on_msg(cfg: RaftConfig, w: RaftState, now, pay, rand):
    dst, mtype, src, mterm = pay[0], pay[1], pay[2], pay[3]
    a, b, c, d = pay[4], pay[5], pay[6], pay[7]
    live = get1(efaults.up(w.fstate), dst)
    role_dst = get1(w.role, dst)
    was_leader = live & (role_dst == LEADER)

    # term catch-up (Raft §5.1): any message with a higher term demotes
    higher = live & (mterm > get1(w.term, dst))
    term_d = jnp.where(higher, mterm, get1(w.term, dst))
    role_d = jnp.where(higher, FOLLOWER, role_dst)
    voted_d = jnp.where(higher, -1, get1(w.voted, dst))

    is_rv = live & (mtype == M_REQ_VOTE)
    is_vg = live & (mtype == M_VOTE_GRANT)
    is_ap = live & (mtype == M_APPEND)
    is_ar = live & (mtype == M_APPEND_RSP)

    log_row = get1(w.log_term, dst)  # [L] this node's log terms
    my_len = get1(w.log_len, dst)

    # -- RequestVote (§5.4.1 up-to-date restriction): grant iff same term,
    # not voted for anyone else, and candidate log >= ours
    my_last_term = geti(log_row, my_len[None])[0]
    log_ok = (b > my_last_term) | ((b == my_last_term) & (a >= my_len))
    grant = (
        is_rv
        & (mterm == term_d)
        & ((voted_d == -1) | (voted_d == src))
        & log_ok
    )
    voted_d = jnp.where(grant, src, voted_d)

    # -- VoteGrant: count iff still candidate in that term
    counted = is_vg & (role_d == CANDIDATE) & (mterm == term_d)
    src_bit = jnp.left_shift(jnp.uint32(1), src.astype(jnp.uint32))
    votes_d = jnp.where(counted, get1(w.votes, dst) | src_bit, get1(w.votes, dst))
    majority = cfg.num_nodes // 2 + 1
    won = counted & (jax.lax.population_count(votes_d).astype(jnp.int32) >= majority)
    role_d = jnp.where(won, LEADER, role_d)

    # -- AppendEntries: same-term leader signal; consistency-check and
    # append the carried entry; follow the leader's commit
    heard = is_ap & (mterm == term_d)
    role_d = jnp.where(heard & (role_d == CANDIDATE), FOLLOWER, role_d)
    prev_idx, prev_term, ent_term, leader_commit = a, b, c, d
    consistent = heard & (prev_idx <= my_len) & (
        geti(log_row, prev_idx[None])[0] == prev_term
    )
    has_entry = ent_term > 0
    slot_idx = prev_idx + 1
    can_store = slot_idx < cfg.log_cap
    store = consistent & has_entry & can_store
    overflow = consistent & has_entry & ~can_store
    # Raft §5.3 append rule: if the slot already holds this entry (same
    # term) keep the existing suffix; a conflicting entry truncates the
    # log at the new entry's index
    existing_same = (slot_idx <= my_len) & (
        geti(log_row, jnp.minimum(slot_idx, cfg.log_cap - 1)[None])[0] == ent_term
    )
    new_len = jnp.where(
        store,
        jnp.where(existing_same, my_len, slot_idx),
        my_len,
    )

    lepoch_dst = get1(w.lepoch, dst)
    w2 = w._replace(
        term=set1(w.term, dst, term_d),
        role=set1(w.role, dst, role_d),
        voted=set1(w.voted, dst, voted_d),
        votes=set1(w.votes, dst, votes_d),
        lepoch=set1(w.lepoch, dst, lepoch_dst + 1, won),
        last_hb=set1(w.last_hb, dst, now, heard | grant | won),
        log_term=set2(w.log_term, dst, slot_idx, ent_term, store),
        log_len=set1(w.log_len, dst, new_len),
        log_overflow=w.log_overflow | overflow,
    )
    w2 = _record_election(cfg, w2, term_d, dst, won)
    # follower commit: min(leader_commit, own len) once consistent
    w2 = _advance_commit(
        cfg, w2, dst, jnp.minimum(leader_commit, get1(w2.log_len, dst)), consistent
    )

    # -- AppendEntries response (leader side): update next/match, advance
    # commit under the §5.4.2 current-term rule
    rsp_ok = is_ar & (mterm == term_d) & (role_d == LEADER)
    success = a == 1
    old_match = get2(w2.match_idx, dst, src)
    old_next = get2(w2.next_idx, dst, src)
    new_match = jnp.where(rsp_ok & success, jnp.maximum(old_match, b), old_match)
    new_next = jnp.where(
        rsp_ok,
        jnp.where(success, new_match + 1, jnp.maximum(old_next - 1, 1)),
        old_next,
    )
    w2 = w2._replace(
        match_idx=set2(w2.match_idx, dst, src, new_match),
        next_idx=set2(w2.next_idx, dst, src, new_next),
    )
    # commit: highest idx replicated on a majority with an entry of the
    # leader's current term
    idxs = jnp.arange(cfg.log_cap, dtype=jnp.int32)
    self_mask = jnp.arange(cfg.num_nodes, dtype=jnp.int32) == dst
    match_row = get1(w2.match_idx, dst)  # [N]
    # replicas[i] = 1 + #followers with match_idx >= i
    reps = 1 + jnp.sum(
        (match_row[None, :] >= idxs[:, None]) & ~self_mask[None, :],
        axis=1, dtype=jnp.int32,
    )
    my_len2 = get1(w2.log_len, dst)
    log_row2 = get1(w2.log_term, dst)
    committable = (
        (idxs <= my_len2)
        & (idxs > get1(w2.commit, dst))
        & (reps >= majority)
        & (log_row2 == term_d)
    )
    best = jnp.max(jnp.where(committable, idxs, 0))
    w2 = _advance_commit(cfg, w2, dst, best, rsp_ok & (best > 0))

    # a leader demoted by a higher term must re-enter the election-timer
    # chain (its own timer chain ended when it fired during leadership)
    demoted = was_leader & (role_d != LEADER)
    tgen_dst = get1(w.tgen, dst)
    tgen_d = jnp.where(demoted, tgen_dst + 1, tgen_dst)
    w2 = w2._replace(tgen=set1(w2.tgen, dst, tgen_d))

    # on win: reset leader bookkeeping and broadcast immediate heartbeats
    init_next = get1(w2.log_len, dst) + 1
    w2 = w2._replace(
        next_idx=set1(w2.next_idx, dst, init_next, won),
        match_idx=set1(w2.match_idx, dst, 0, won),
    )
    bcast, sent, delivered = _broadcast(
        cfg, w2, now, dst, rand, won, _append_pays(cfg, w2, dst, term_d)
    )
    # extra slot 1: heartbeat timer (won) | vote reply (grant) | append rsp
    rt, rdeliver = enet.route(
        w.links, now, dst, src, rand[2 * cfg.num_nodes], rand[2 * cfg.num_nodes + 1]
    )
    ap_success = jnp.where(consistent, 1, 0)
    ap_match = jnp.where(store, slot_idx, jnp.minimum(prev_idx, get1(w2.log_len, dst)))
    reply_pay = jnp.where(
        grant,
        _pay(src, M_VOTE_GRANT, dst, mterm),
        _pay(src, M_APPEND_RSP, dst, term_d, ap_success, ap_match),
    )
    attempt_reply = (grant | is_ap) & live
    send_reply = attempt_reply & rdeliver
    hb = efaults.skewed_delay(
        fault_spec(cfg), w.fstate, dst, cfg.heartbeat_ns, rt=_rt(cfg, w)
    )
    extra_time = jnp.where(won, now + hb, rt)
    extra_kind = jnp.where(won, jnp.int32(K_HEARTBEAT), jnp.int32(K_MSG))
    extra_pay = jnp.where(won, _pay(dst, get1(w2.lepoch, dst)), reply_pay)
    extra_on = won | (send_reply & ~won)
    # extra slot 2: the demoted ex-leader's fresh election timer
    retimeout = efaults.skewed_delay(
        fault_spec(cfg), w.fstate, dst,
        bounded(
            rand[2 * cfg.num_nodes + 2], cfg.election_lo_ns, cfg.election_hi_ns
        ),
        rt=_rt(cfg, w),
    )
    emits = _emits(
        cfg,
        bcast,
        (extra_time, extra_kind, extra_pay, extra_on),
        (now + retimeout, K_ELECTION, _pay(dst, tgen_d), demoted),
    )
    # sent counts every attempted reply (like the broadcast path, which
    # counts all N-1 regardless of the link test); delivered only those
    # that passed the link test
    w2 = w2._replace(
        msgs_sent=w2.msgs_sent + sent + jnp.where(attempt_reply, 1, 0),
        msgs_delivered=w2.msgs_delivered + delivered + jnp.where(send_reply, 1, 0),
    )
    return w2, emits


def _on_fault(cfg: RaftConfig, w: RaftState, now, pay, rand):
    """One event of the compiled fault campaign (engine/faults.py). The
    shared interpreter updates liveness/pause masks and the LinkState;
    this handler adds the Raft-specific consequences:

    - crash: volatile state resets (role, votes, commit) while durable
      state (term, voted, log) survives — ref kill semantics
      task/mod.rs:347-364 — plus the amnesia wipe in ``volatile_state``
      mode; timer chains are invalidated by generation bumps.
    - pause: timer chains are invalidated the same way (the paused node's
      clock stops), but no state is lost.
    - restart/resume: a restarted (or resumed non-leader) node re-enters
      the election-timer chain; a resumed LEADER keeps its role, so it
      re-enters the heartbeat chain instead — as on the host tier, where
      ``Handle.resume`` lets the leader's tasks heartbeat on (a deposed
      leader's election timer comes from the demotion path in _on_msg).
    """
    action, victim = pay[0], pay[1]
    base = efaults.NetBase(cfg.lat_lo_ns, cfg.lat_hi_ns, cfg.loss_q32)
    links2, f2, e = efaults.on_event(
        _rt(cfg, w), base, w.links, w.fstate, action, victim
    )
    crashed, restarted, resumed = e.crashed, e.restarted, e.resumed
    stopped = crashed | e.paused  # the node's event chains must die
    revived = restarted | resumed  # the node needs a fresh timer chain

    rollback = {}
    if _shadow_nodes(cfg):
        # durability rollback (crash OR power_fail edge): the "durable"
        # state reverts to its synced shadow — an identity outside
        # slow-disk windows, where every mutation synced immediately.
        # Statically absent when the spec draws no stall windows (the
        # shadow planes are width-0 then).
        rollback = dict(
            term=set1(w.term, victim, get1(w.dur_term, victim), crashed),
            voted=set1(w.voted, victim, get1(w.dur_voted, victim), crashed),
            log_len=set1(
                w.log_len, victim, get1(w.dur_log_len, victim), crashed
            ),
            log_term=set1(
                w.log_term, victim, get1(w.dur_log_term, victim), crashed
            ),
        )
    w2 = w._replace(
        links=links2,
        fstate=f2,
        role=set1(w.role, victim, FOLLOWER, crashed | restarted),
        votes=set1(w.votes, victim, jnp.uint32(0), crashed),
        commit=set1(w.commit, victim, 0, crashed),
        tgen=set1(w.tgen, victim, get1(w.tgen, victim) + 1, stopped),
        lepoch=set1(w.lepoch, victim, get1(w.lepoch, victim) + 1, stopped),
        last_hb=set1(w.last_hb, victim, now, revived),
        **rollback,
    )
    if cfg.volatile_state:
        # amnesia mode: the "durable" state dies with the process too
        # (what host-tier code that keeps everything in memory does) —
        # the shadows are wiped as well, else the NEXT crash would
        # resurrect pre-amnesia state out of them
        zlog = jnp.zeros((cfg.log_cap,), jnp.int32)
        w2 = w2._replace(
            term=set1(w2.term, victim, 0, crashed),
            voted=set1(w2.voted, victim, -1, crashed),
            log_len=set1(w2.log_len, victim, 0, crashed),
            log_term=set1(w2.log_term, victim, zlog, crashed),
        )
        if _shadow_nodes(cfg):
            w2 = w2._replace(
                dur_term=set1(w2.dur_term, victim, 0, crashed),
                dur_voted=set1(w2.dur_voted, victim, -1, crashed),
                dur_log_len=set1(w2.dur_log_len, victim, 0, crashed),
                dur_log_term=set1(w2.dur_log_term, victim, zlog, crashed),
            )
    timeout = efaults.skewed_delay(
        fault_spec(cfg), f2, victim,
        bounded(rand[0], cfg.election_lo_ns, cfg.election_hi_ns),
        rt=_rt(cfg, w),
    )
    still_leader = get1(w2.role, victim) == LEADER  # only a resumed leader
    hb = efaults.skewed_delay(
        fault_spec(cfg), f2, victim, cfg.heartbeat_ns, rt=_rt(cfg, w)
    )
    emits = _emits(
        cfg,
        _no_bcast(cfg),
        (
            now + timeout,
            K_ELECTION,
            _pay(victim, get1(w2.tgen, victim)),
            revived & ~still_leader,
        ),
        (
            now + hb,
            K_HEARTBEAT,
            _pay(victim, get1(w2.lepoch, victim)),
            resumed & still_leader,
        ),
    )
    return w2, emits


def _on_cmd(cfg: RaftConfig, w: RaftState, now, pay, rand):
    """A client command looking for the leader: if the target node is a
    live leader with log room, append an entry of its term; otherwise
    retry against the next node after cmd_retry_ns."""
    target, retries = pay[0], pay[1]
    is_leader = get1(efaults.up(w.fstate), target) & (
        get1(w.role, target) == LEADER
    )
    slot = get1(w.log_len, target) + 1
    room = slot < cfg.log_cap
    accept = is_leader & room
    w2 = w._replace(
        log_term=set2(w.log_term, target, slot, get1(w.term, target), accept),
        log_len=set1(w.log_len, target, slot, accept),
        log_overflow=w.log_overflow | (is_leader & ~room),
        accepted_cmds=w.accepted_cmds + jnp.where(accept, 1, 0),
    )
    next_target = (target + 1) % cfg.num_nodes
    give_up = ~accept & (retries + 1 >= cfg.cmd_max_retries)
    w2 = w2._replace(cmd_giveups=w2.cmd_giveups + jnp.where(give_up, 1, 0))
    emits = _emits(
        cfg,
        _no_bcast(cfg),
        (
            now + cfg.cmd_retry_ns,
            K_CMD,
            _pay(next_target, retries + 1),
            ~accept & ~give_up,
        ),
        _DISABLED_EXTRA,
    )
    return w2, emits


def cover_bits(cfg: RaftConfig) -> int:
    """Size of the coverage bitmap: one bit per (event kind, node, role
    transition) plus one bit per violation flavor."""
    return N_KINDS * cfg.num_nodes * N_ROLE_TRANS + 2


def _cover(cfg: RaftConfig, wb: RaftState, wa: RaftState, now, kind, pay):
    """Map one dispatched event to its coverage bit (engine contract:
    ``Workload.cover``). The bit is (kind x node x role-transition) — the
    swarm-testing signal: a campaign that makes a node take a role
    transition under an event kind no earlier spec reached lights a new
    bit. A newly latched violation flavor claims the event's bit instead
    (flavor bits are the rarest, most valuable coverage)."""
    node = jnp.where(kind == K_FAULT, pay[1], pay[0])
    node = jnp.clip(node, 0, cfg.num_nodes - 1)
    trans = get1(wb.role, node) * 3 + get1(wa.role, node)
    bit = (kind * cfg.num_nodes + node) * N_ROLE_TRANS + trans
    base = N_KINDS * cfg.num_nodes * N_ROLE_TRANS
    new_viol = wa.viol_kind & ~wb.viol_kind
    return jnp.where(
        new_viol != 0,
        base + jnp.where((new_viol & V_ELECTION) != 0, 0, 1),
        bit,
    )


def _probe(w: RaftState):
    """Violation-flavor bitmask (engine contract: ``Workload.probe``) —
    recorded per step by ``run_traced`` so triage can locate the first
    violating event."""
    return w.viol_kind


def _record(cfg: RaftConfig, wb: RaftState, wa: RaftState, now, kind, pay):
    """Map one dispatched event to its op-history record (engine
    contract: ``Workload.record`` — at most ONE row per event).

    Raft records leadership: each won election appends one OP_ELECT
    *invoke* row (client = winner node, key = the won term, inp = the
    node again; there is no client-observed completion, so the op stays
    open — ``oracle.specs.ElectionSpec`` is a structural check over
    invoke rows). The host tier records the same rows through
    ``HostRecorder`` in ``examples/raft_host.py``, so one sequential
    spec checks both tiers (explore/differential.py)."""
    won = wa.elections > wb.elections
    # the only win sites are K_MSG handlers, where pay[0] is the winner
    node = jnp.clip(pay[0], 0, cfg.num_nodes - 1)
    term = get1(wa.term, node)
    rec = jnp.stack(
        [
            node,
            jnp.full((), OP_ELECT * 2 + PH_INVOKE, jnp.int32),
            term,
            node,
            wb.elections,  # opid: the global election counter
        ]
    )
    return rec, won


def _handle(cfg: RaftConfig, w: RaftState, now, kind, pay, rand):
    branches = [
        partial(_on_election_timer, cfg),
        partial(_on_heartbeat_timer, cfg),
        partial(_on_msg, cfg),
        partial(_on_fault, cfg),
        partial(_on_cmd, cfg),
    ]
    w2, emits = jax.lax.switch(kind, branches, w, now, pay, rand)
    # durability plane: fsync-on-mutate — after every event each node's
    # synced shadow catches up to the live durable state UNLESS a
    # slow-disk window holds its fsync (engine/faults.stalled), in which
    # case the shadow freezes and a crash/power_fail rolls back to it.
    # One vectorized masked write per event, statically gated off (with
    # width-0 planes) for specs that draw no stall windows.
    if _shadow_nodes(cfg):
        sync = ~efaults.stalled(w2.fstate)
        w2 = w2._replace(
            dur_term=jnp.where(sync, w2.term, w2.dur_term),
            dur_voted=jnp.where(sync, w2.voted, w2.dur_voted),
            dur_log_len=jnp.where(sync, w2.log_len, w2.dur_log_len),
            dur_log_term=jnp.where(sync[:, None], w2.log_term, w2.dur_log_term),
        )
    return w2, emits


def _init(cfg: RaftConfig, key, params=None):
    n = cfg.num_nodes
    ninit = n + cfg.commands
    # init draws live in their own counter namespace, disjoint from the
    # per-event stream (event counters stay far below 2**31) and from the
    # fault-schedule namespace (engine/faults.FAULT_STREAM)
    rand = jax.random.bits(
        jax.random.fold_in(key, 0x7FFF_FFFF),
        (n + 2 * cfg.commands,),
        dtype=jnp.uint32,
    )
    w = RaftState(
        role=jnp.zeros((n,), jnp.int32),
        term=jnp.zeros((n,), jnp.int32),
        voted=jnp.full((n,), -1, jnp.int32),
        votes=jnp.zeros((n,), jnp.uint32),
        fstate=efaults.init_state(n),
        last_hb=jnp.zeros((n,), jnp.int64),
        tgen=jnp.zeros((n,), jnp.int32),
        lepoch=jnp.zeros((n,), jnp.int32),
        log_term=jnp.zeros((n, cfg.log_cap), jnp.int32),
        log_len=jnp.zeros((n,), jnp.int32),
        dur_term=jnp.zeros((_shadow_nodes(cfg),), jnp.int32),
        dur_voted=jnp.full((_shadow_nodes(cfg),), -1, jnp.int32),
        dur_log_term=jnp.zeros((_shadow_nodes(cfg), cfg.log_cap), jnp.int32),
        dur_log_len=jnp.zeros((_shadow_nodes(cfg),), jnp.int32),
        commit=jnp.zeros((n,), jnp.int32),
        next_idx=jnp.ones((n, n), jnp.int32),
        match_idx=jnp.zeros((n, n), jnp.int32),
        links=enet.make(
            n, cfg.loss_q32, cfg.lat_lo_ns, cfg.lat_hi_ns, cfg.buggify_q32
        ),
        hist_term=jnp.zeros((cfg.history,), jnp.int32),
        hist_node=jnp.zeros((cfg.history,), jnp.int32),
        hist_valid=jnp.zeros((cfg.history,), bool),
        hist_pos=jnp.zeros((), jnp.int32),
        chist_term=jnp.zeros((cfg.log_cap,), jnp.int32),
        chist_set=jnp.zeros((cfg.log_cap,), bool),
        violation=jnp.zeros((), bool),
        viol_kind=jnp.zeros((), jnp.int32),
        log_overflow=jnp.zeros((), bool),
        elections=jnp.zeros((), jnp.int32),
        commits=jnp.zeros((), jnp.int32),
        accepted_cmds=jnp.zeros((), jnp.int32),
        cmd_giveups=jnp.zeros((), jnp.int32),
        msgs_sent=jnp.zeros((), jnp.int32),
        msgs_delivered=jnp.zeros((), jnp.int32),
        frt=efaults.make_rt(fault_spec(cfg), params),
    )
    times = jnp.zeros((ninit,), jnp.int64)
    kinds = jnp.zeros((ninit,), jnp.int32)
    pays = jnp.zeros((ninit, PAYLOAD_SLOTS), jnp.int32)
    enables = jnp.ones((ninit,), bool)
    # one election timer per node
    for i in range(n):
        times = times.at[i].set(bounded(rand[i], cfg.election_lo_ns, cfg.election_hi_ns))
        kinds = kinds.at[i].set(K_ELECTION)
        pays = pays.at[i].set(_pay(i, 0))
    # client command plan
    for k in range(cfg.commands):
        t_cmd = bounded(rand[n + 2 * k], 0, cfg.cmd_window_ns)
        target = bounded(rand[n + 2 * k + 1], 0, n).astype(jnp.int32)
        times = times.at[n + k].set(t_cmd)
        kinds = kinds.at[n + k].set(K_CMD)
        pays = pays.at[n + k].set(_pay(target, 0))
    # fault campaign: the shared compiler's event stream, spliced in
    fe = efaults.compile_device(
        fault_spec(cfg), n, key, K_FAULT, PAYLOAD_SLOTS, params=params
    )
    return w, Emits(
        times=jnp.concatenate([times, fe.times]),
        kinds=jnp.concatenate([kinds, fe.kinds]),
        pays=jnp.concatenate([pays, fe.pays]),
        enables=jnp.concatenate([enables, fe.enables]),
    )


def history_spec():
    """The sequential spec this model's recorded histories check
    against (oracle/specs.ElectionSpec) — also the key the device
    screen dispatches on (oracle/screen.screen_for), so a checked sweep
    needs no per-call-site spec plumbing."""
    from ..oracle.specs import ElectionSpec

    return ElectionSpec()


@_common.memoized_workload(RaftConfig)
def workload(cfg: RaftConfig = None) -> Workload:
    """Build the engine Workload for a Raft sweep configuration
    (memoized per config — see _common.memoized_workload)."""
    return Workload(
        init=partial(_init, cfg),
        handle=partial(_handle, cfg),
        num_rand=2 * cfg.num_nodes + 3,
        payload_slots=PAYLOAD_SLOTS,
        max_emits=cfg.num_nodes + 2,
        cover=partial(_cover, cfg),
        cover_bits=cover_bits(cfg),
        probe=_probe,
        record=partial(_record, cfg) if cfg.hist_slots > 0 else None,
        hist_slots=cfg.hist_slots,
        event_mix_kinds=N_KINDS if cfg.event_mix else 0,
    )


def engine_config(cfg: RaftConfig = RaftConfig(), **overrides) -> EngineConfig:
    """Engine parameters sized for this workload.

    Queue sizing: steady state holds ≤1 election timer + ≤1 heartbeat
    timer per node, ≤1 in-flight broadcast (N-1 messages) per node plus
    replies, and the pending fault/command plan. 2N² + plans covers that
    with ~2x headroom (measured high-water at N=5 is ~30; overflow is a
    sticky per-seed flag and ``qmax`` reports the real high-water mark,
    so an undersized queue is observable, never silent)."""
    defaults = dict(
        queue_capacity=max(
            48,
            2 * cfg.num_nodes * cfg.num_nodes
            + cfg.commands
            + efaults.num_events(fault_spec(cfg)),
        ),
        time_limit_ns=10_000_000_000,
        max_steps=200_000,
    )
    defaults.update(overrides)
    return EngineConfig(**defaults)


# one jitted device program for the whole summary (one transfer) — see
# _common.make_sweep_summary
sweep_summary = _common.make_sweep_summary(
    (
        ("violations", lambda f: f.wstate.violation),
        ("elections_total", lambda f: f.wstate.elections),
        ("no_leader_seeds", lambda f: f.wstate.elections == 0),
        ("commits_total", lambda f: f.wstate.commits),
        ("accepted_cmds", lambda f: f.wstate.accepted_cmds),
        ("cmd_giveups", lambda f: f.wstate.cmd_giveups),
        ("log_overflow_seeds", lambda f: f.wstate.log_overflow),
        ("msgs_sent", lambda f: f.wstate.msgs_sent),
        ("msgs_delivered", lambda f: f.wstate.msgs_delivered),
    )
)
