"""Raft leader election as a device workload (the MadRaft sweep).

This is the flagship model for the engine: an N-node Raft cluster (election
+ heartbeats, Ongaro & Ousterhout §5.2) with crash/restart fault injection
and per-message loss/latency, expressed as pure array handlers so thousands
of seeds run in lockstep on TPU. It plays the role the MadRaft test suite
plays for the reference (BASELINE.md configs #3/#5): randomized schedules +
faults hunting for election-safety violations, with every found seed
replayable bit-exactly on CPU via ``engine.run_traced``.

Mechanics mirrored from the reference simulator rather than any Raft
implementation: message delivery = link test + latency draw
(madsim/src/sim/net/network.rs:261-269), node crash/restart semantics =
kill/restart with durable vs volatile state
(madsim/src/sim/task/mod.rs:347-394), randomized timers = the virtual-clock
timer queue (madsim/src/sim/time/mod.rs:142-153).

Design notes:
- Timer staleness uses generation counters (``tgen`` per node for election
  timers, ``lepoch`` per node for heartbeat timers) instead of timer
  cancellation — the queue is append-only per event, cancellation is a
  pay-mismatch drop, which costs nothing in lockstep.
- Election safety is checked online: every won election is recorded in a
  small (term, node) ring; a second winner of an already-recorded term
  raises the sticky ``violation`` flag.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..engine import net as enet
from ..engine.core import Emits, EngineConfig, Workload
from ..engine.rng import bounded, prob_to_q32
from ..engine.queue import INVALID_TIME

# event kinds
K_ELECTION = 0  # pay = (node, tgen)
K_HEARTBEAT = 1  # pay = (node, lepoch)
K_MSG = 2  # pay = (dst, mtype, src, term)
K_CRASH = 3  # pay = (node,)
K_RESTART = 4  # pay = (node,)

# message types
M_REQ_VOTE = 0
M_VOTE_GRANT = 1
M_APPEND = 2

# roles
FOLLOWER = 0
CANDIDATE = 1
LEADER = 2

PAYLOAD_SLOTS = 4


class RaftConfig(NamedTuple):
    """Static sweep parameters (hashable — part of the jit key)."""

    num_nodes: int = 5
    election_lo_ns: int = 150_000_000
    election_hi_ns: int = 300_000_000
    heartbeat_ns: int = 50_000_000
    # fault plan: `crashes` node-crash events at random times in the first
    # `crash_window_ns`, each restarting after a random delay
    crashes: int = 2
    crash_window_ns: int = 5_000_000_000
    restart_lo_ns: int = 100_000_000
    restart_hi_ns: int = 1_000_000_000
    # network model (reference defaults: 1-10 ms latency, lossless)
    loss_q32: int = prob_to_q32(0.01)
    lat_lo_ns: int = 1_000_000
    lat_hi_ns: int = 10_000_000
    history: int = 16  # election-safety ring size


class RaftState(NamedTuple):
    # per-node Raft state [N]
    role: jnp.ndarray  # int32
    term: jnp.ndarray  # int32
    voted: jnp.ndarray  # int32, -1 = none (durable)
    votes: jnp.ndarray  # uint32 bitmask of granted votes
    alive: jnp.ndarray  # bool
    last_hb: jnp.ndarray  # int64, last time a valid leader/grant was heard
    tgen: jnp.ndarray  # int32 election-timer generation
    lepoch: jnp.ndarray  # int32 leadership epoch (heartbeat-timer guard)
    # network
    links: enet.LinkState
    # election-safety ring [H]
    hist_term: jnp.ndarray  # int32
    hist_node: jnp.ndarray  # int32
    hist_valid: jnp.ndarray  # bool
    hist_pos: jnp.ndarray  # int32
    # sweep outputs
    violation: jnp.ndarray  # bool
    elections: jnp.ndarray  # int32
    msgs_sent: jnp.ndarray  # int32
    msgs_delivered: jnp.ndarray  # int32


def _pay(*vals, slots: int = PAYLOAD_SLOTS) -> jnp.ndarray:
    out = jnp.zeros((slots,), jnp.int32)
    for i, v in enumerate(vals):
        out = out.at[i].set(jnp.asarray(v, jnp.int32))
    return out


def _broadcast(cfg: RaftConfig, w: RaftState, now, src, mtype, term, rand, enable):
    """Emit slots 0..N-1: one message per destination node (self slot
    disabled), each individually link-tested (loss/clog/latency draws)."""
    n = cfg.num_nodes
    times = jnp.zeros((n,), jnp.int64)
    kinds = jnp.full((n,), K_MSG, jnp.int32)
    pays = jnp.zeros((n, PAYLOAD_SLOTS), jnp.int32)
    enables = jnp.zeros((n,), bool)
    for i in range(n):
        t, deliver = enet.route(w.links, now, src, jnp.int32(i), rand[2 * i], rand[2 * i + 1])
        on = enable & (i != src) & deliver
        times = times.at[i].set(t)
        pays = pays.at[i].set(_pay(i, mtype, src, term))
        enables = enables.at[i].set(on)
    sent = jnp.where(enable, jnp.int32(cfg.num_nodes - 1), 0)
    delivered = jnp.sum(enables, dtype=jnp.int32)
    return times, kinds, pays, enables, sent, delivered


_DISABLED_EXTRA = None  # sentinel: an unused extra slot


def _emits(cfg: RaftConfig, bcast, *extras) -> Emits:
    """Pack N broadcast slots + 2 extra slots (timers/replies) into Emits.

    Each extra is ``(time, kind, pay, enable)`` or None (disabled slot);
    every handler emits the same fixed shape (N+2 events)."""
    times, kinds, pays, enables = bcast
    assert len(extras) == 2
    for extra in extras:
        if extra is None:
            et = jnp.zeros((), jnp.int64)
            ek = jnp.zeros((), jnp.int32)
            ep = jnp.zeros((PAYLOAD_SLOTS,), jnp.int32)
            eo = jnp.zeros((), bool)
        else:
            et, ek, ep, eo = extra
            et = jnp.asarray(et, jnp.int64)
            ek = jnp.asarray(ek, jnp.int32)
            eo = jnp.asarray(eo, bool)
        times = jnp.concatenate([times, et[None]])
        kinds = jnp.concatenate([kinds, ek[None]])
        pays = jnp.concatenate([pays, ep[None]])
        enables = jnp.concatenate([enables, eo[None]])
    return Emits(times=times, kinds=kinds, pays=pays, enables=enables)


def _no_bcast(cfg: RaftConfig):
    n = cfg.num_nodes
    return (
        jnp.zeros((n,), jnp.int64),
        jnp.full((n,), K_MSG, jnp.int32),
        jnp.zeros((n, PAYLOAD_SLOTS), jnp.int32),
        jnp.zeros((n,), bool),
    )


def _record_election(cfg: RaftConfig, w: RaftState, term, node, won):
    """Online election-safety check: a term may elect at most one leader."""
    dup = jnp.any(w.hist_valid & (w.hist_term == term) & (w.hist_node != node))
    slot = w.hist_pos % cfg.history
    return w._replace(
        violation=w.violation | (won & dup),
        hist_term=w.hist_term.at[slot].set(jnp.where(won, term, w.hist_term[slot])),
        hist_node=w.hist_node.at[slot].set(jnp.where(won, node, w.hist_node[slot])),
        hist_valid=w.hist_valid.at[slot].set(w.hist_valid[slot] | won),
        hist_pos=jnp.where(won, w.hist_pos + 1, w.hist_pos),
        elections=jnp.where(won, w.elections + 1, w.elections),
    )


# -- event handlers (each: (w, now, pay, rand) -> (w, Emits)) ---------------


def _on_election_timer(cfg: RaftConfig, w: RaftState, now, pay, rand):
    node, gen = pay[0], pay[1]
    valid = w.alive[node] & (gen == w.tgen[node]) & (w.role[node] != LEADER)
    # a live leader/candidate signal arrived since this timer was armed?
    recent = (w.last_hb[node] + cfg.election_lo_ns) > now
    starting = valid & ~recent

    new_term = w.term[node] + 1
    self_bit = jnp.left_shift(jnp.uint32(1), node.astype(jnp.uint32))
    w2 = w._replace(
        term=w.term.at[node].set(jnp.where(starting, new_term, w.term[node])),
        role=w.role.at[node].set(jnp.where(starting, CANDIDATE, w.role[node])),
        voted=w.voted.at[node].set(jnp.where(starting, node, w.voted[node])),
        votes=w.votes.at[node].set(jnp.where(starting, self_bit, w.votes[node])),
        last_hb=w.last_hb.at[node].set(jnp.where(starting, now, w.last_hb[node])),
    )
    bcast = _broadcast(cfg, w2, now, node, M_REQ_VOTE, new_term, rand, starting)
    timeout = bounded(rand[2 * cfg.num_nodes], cfg.election_lo_ns, cfg.election_hi_ns)
    emits = _emits(
        cfg,
        bcast[:4],
        # one live election timer per node, always re-armed while valid
        (now + timeout, K_ELECTION, _pay(node, w.tgen[node]), valid),
        _DISABLED_EXTRA,
    )
    w2 = w2._replace(
        msgs_sent=w2.msgs_sent + bcast[4], msgs_delivered=w2.msgs_delivered + bcast[5]
    )
    return w2, emits


def _on_heartbeat_timer(cfg: RaftConfig, w: RaftState, now, pay, rand):
    node, epoch = pay[0], pay[1]
    valid = w.alive[node] & (w.role[node] == LEADER) & (epoch == w.lepoch[node])
    bcast = _broadcast(cfg, w, now, node, M_APPEND, w.term[node], rand, valid)
    emits = _emits(
        cfg,
        bcast[:4],
        (now + cfg.heartbeat_ns, K_HEARTBEAT, _pay(node, epoch), valid),
        _DISABLED_EXTRA,
    )
    w2 = w._replace(
        msgs_sent=w.msgs_sent + bcast[4], msgs_delivered=w.msgs_delivered + bcast[5]
    )
    return w2, emits


def _on_msg(cfg: RaftConfig, w: RaftState, now, pay, rand):
    dst, mtype, src, mterm = pay[0], pay[1], pay[2], pay[3]
    live = w.alive[dst]
    was_leader = live & (w.role[dst] == LEADER)

    # term catch-up (Raft §5.1): any message with a higher term demotes
    higher = live & (mterm > w.term[dst])
    term_d = jnp.where(higher, mterm, w.term[dst])
    role_d = jnp.where(higher, FOLLOWER, w.role[dst])
    voted_d = jnp.where(higher, -1, w.voted[dst])

    is_rv = live & (mtype == M_REQ_VOTE)
    is_vg = live & (mtype == M_VOTE_GRANT)
    is_ap = live & (mtype == M_APPEND)

    # RequestVote: grant iff same term and not voted for anyone else
    grant = is_rv & (mterm == term_d) & ((voted_d == -1) | (voted_d == src))
    voted_d = jnp.where(grant, src, voted_d)

    # VoteGrant: count iff still candidate in that term
    counted = is_vg & (role_d == CANDIDATE) & (mterm == term_d)
    src_bit = jnp.left_shift(jnp.uint32(1), src.astype(jnp.uint32))
    votes_d = jnp.where(counted, w.votes[dst] | src_bit, w.votes[dst])
    majority = cfg.num_nodes // 2 + 1
    won = counted & (jax.lax.population_count(votes_d).astype(jnp.int32) >= majority)
    role_d = jnp.where(won, LEADER, role_d)

    # AppendEntries (heartbeat): same-term leader signal resets the
    # election timer basis and demotes a same-term candidate
    heard = is_ap & (mterm == term_d)
    role_d = jnp.where(heard & (role_d == CANDIDATE), FOLLOWER, role_d)
    reset_hb = heard | grant | won

    # a leader demoted by a higher term must re-enter the election-timer
    # chain (its own timer chain ended when it fired during leadership);
    # bump tgen so any stale timer stays dead, then arm a fresh one below
    demoted = was_leader & (role_d != LEADER)
    tgen_d = jnp.where(demoted, w.tgen[dst] + 1, w.tgen[dst])

    w2 = w._replace(
        term=w.term.at[dst].set(term_d),
        role=w.role.at[dst].set(role_d),
        voted=w.voted.at[dst].set(voted_d),
        votes=w.votes.at[dst].set(votes_d),
        tgen=w.tgen.at[dst].set(tgen_d),
        lepoch=w.lepoch.at[dst].set(jnp.where(won, w.lepoch[dst] + 1, w.lepoch[dst])),
        last_hb=w.last_hb.at[dst].set(jnp.where(reset_hb, now, w.last_hb[dst])),
    )
    w2 = _record_election(cfg, w2, term_d, dst, won)

    # on win: broadcast immediate heartbeats + arm the heartbeat timer
    bcast = _broadcast(cfg, w2, now, dst, M_APPEND, term_d, rand, won)
    # extra slot: either the heartbeat timer (won) or the vote reply (grant)
    # — mutually exclusive by message type
    rt, rdeliver = enet.route(
        w.links, now, dst, src, rand[2 * cfg.num_nodes], rand[2 * cfg.num_nodes + 1]
    )
    extra_time = jnp.where(won, now + cfg.heartbeat_ns, rt)
    extra_kind = jnp.where(won, jnp.int32(K_HEARTBEAT), jnp.int32(K_MSG))
    extra_pay = jnp.where(
        won,
        _pay(dst, w2.lepoch[dst]),
        _pay(src, M_VOTE_GRANT, dst, mterm),
    )
    extra_on = won | (grant & rdeliver)
    # second extra: the demoted ex-leader's fresh election timer
    retimeout = bounded(
        rand[2 * cfg.num_nodes + 2], cfg.election_lo_ns, cfg.election_hi_ns
    )
    emits = _emits(
        cfg,
        bcast[:4],
        (extra_time, extra_kind, extra_pay, extra_on),
        (now + retimeout, K_ELECTION, _pay(dst, tgen_d), demoted),
    )
    w2 = w2._replace(
        msgs_sent=w2.msgs_sent + bcast[4] + jnp.where(grant, 1, 0),
        msgs_delivered=w2.msgs_delivered
        + bcast[5]
        + jnp.where(grant & rdeliver, 1, 0),
    )
    return w2, emits


def _on_crash(cfg: RaftConfig, w: RaftState, now, pay, rand):
    node = pay[0]
    # durable state (term, voted) survives; volatile state resets
    # (ref kill semantics: task/mod.rs:347-364 — tasks dropped, state wiped)
    w2 = w._replace(
        alive=w.alive.at[node].set(False),
        role=w.role.at[node].set(FOLLOWER),
        votes=w.votes.at[node].set(jnp.uint32(0)),
        tgen=w.tgen.at[node].set(w.tgen[node] + 1),
        lepoch=w.lepoch.at[node].set(w.lepoch[node] + 1),
    )
    return w2, _emits(cfg, _no_bcast(cfg), _DISABLED_EXTRA, _DISABLED_EXTRA)


def _on_restart(cfg: RaftConfig, w: RaftState, now, pay, rand):
    node = pay[0]
    was_dead = ~w.alive[node]
    w2 = w._replace(
        alive=w.alive.at[node].set(True),
        role=w.role.at[node].set(jnp.where(was_dead, FOLLOWER, w.role[node])),
        last_hb=w.last_hb.at[node].set(jnp.where(was_dead, now, w.last_hb[node])),
    )
    timeout = bounded(rand[0], cfg.election_lo_ns, cfg.election_hi_ns)
    emits = _emits(
        cfg,
        _no_bcast(cfg),
        (now + timeout, K_ELECTION, _pay(node, w2.tgen[node]), was_dead),
        _DISABLED_EXTRA,
    )
    return w2, emits


def _handle(cfg: RaftConfig, w: RaftState, now, kind, pay, rand):
    branches = [
        partial(_on_election_timer, cfg),
        partial(_on_heartbeat_timer, cfg),
        partial(_on_msg, cfg),
        partial(_on_crash, cfg),
        partial(_on_restart, cfg),
    ]
    return jax.lax.switch(kind, branches, w, now, pay, rand)


def _init(cfg: RaftConfig, key):
    n = cfg.num_nodes
    ninit = n + 2 * cfg.crashes
    # init draws live in their own counter namespace, disjoint from the
    # per-event stream (event counters stay far below 2**31)
    rand = jax.random.bits(
        jax.random.fold_in(key, 0x7FFF_FFFF), (ninit + cfg.crashes,), dtype=jnp.uint32
    )
    w = RaftState(
        role=jnp.zeros((n,), jnp.int32),
        term=jnp.zeros((n,), jnp.int32),
        voted=jnp.full((n,), -1, jnp.int32),
        votes=jnp.zeros((n,), jnp.uint32),
        alive=jnp.ones((n,), bool),
        last_hb=jnp.zeros((n,), jnp.int64),
        tgen=jnp.zeros((n,), jnp.int32),
        lepoch=jnp.zeros((n,), jnp.int32),
        links=enet.make(n, cfg.loss_q32, cfg.lat_lo_ns, cfg.lat_hi_ns),
        hist_term=jnp.zeros((cfg.history,), jnp.int32),
        hist_node=jnp.zeros((cfg.history,), jnp.int32),
        hist_valid=jnp.zeros((cfg.history,), bool),
        hist_pos=jnp.zeros((), jnp.int32),
        violation=jnp.zeros((), bool),
        elections=jnp.zeros((), jnp.int32),
        msgs_sent=jnp.zeros((), jnp.int32),
        msgs_delivered=jnp.zeros((), jnp.int32),
    )
    times = jnp.zeros((ninit,), jnp.int64)
    kinds = jnp.zeros((ninit,), jnp.int32)
    pays = jnp.zeros((ninit, PAYLOAD_SLOTS), jnp.int32)
    enables = jnp.ones((ninit,), bool)
    # one election timer per node
    for i in range(n):
        times = times.at[i].set(bounded(rand[i], cfg.election_lo_ns, cfg.election_hi_ns))
        kinds = kinds.at[i].set(K_ELECTION)
        pays = pays.at[i].set(_pay(i, 0))
    # fault plan: crash (node, t) then restart after a random delay
    for c in range(cfg.crashes):
        t_crash = bounded(rand[n + 2 * c], 0, cfg.crash_window_ns)
        delay = bounded(rand[n + 2 * c + 1], cfg.restart_lo_ns, cfg.restart_hi_ns)
        victim = bounded(rand[ninit + c], 0, n).astype(jnp.int32)
        times = times.at[n + 2 * c].set(t_crash)
        kinds = kinds.at[n + 2 * c].set(K_CRASH)
        pays = pays.at[n + 2 * c].set(_pay(victim))
        times = times.at[n + 2 * c + 1].set(t_crash + delay)
        kinds = kinds.at[n + 2 * c + 1].set(K_RESTART)
        pays = pays.at[n + 2 * c + 1].set(_pay(victim))
    return w, Emits(times=times, kinds=kinds, pays=pays, enables=enables)


def workload(cfg: RaftConfig = RaftConfig()) -> Workload:
    """Build the engine Workload for a Raft sweep configuration."""
    return Workload(
        init=partial(_init, cfg),
        handle=partial(_handle, cfg),
        num_rand=2 * cfg.num_nodes + 3,
        payload_slots=PAYLOAD_SLOTS,
        max_emits=cfg.num_nodes + 2,
    )


def engine_config(cfg: RaftConfig = RaftConfig(), **overrides) -> EngineConfig:
    """Engine parameters sized for this workload (queue holds worst-case
    in-flight: N broadcasts from every node + timers + fault plan)."""
    defaults = dict(
        queue_capacity=max(64, 4 * cfg.num_nodes * cfg.num_nodes),
        time_limit_ns=10_000_000_000,
        max_steps=200_000,
    )
    defaults.update(overrides)
    return EngineConfig(**defaults)


def sweep_summary(final) -> dict:
    """Host-side reduction of a finished sweep's batched EngineState."""
    w: RaftState = final.wstate
    import numpy as np

    return {
        "seeds": int(final.seed.shape[0]),
        "violations": int(np.sum(np.asarray(w.violation))),
        "elections_total": int(np.sum(np.asarray(w.elections))),
        "no_leader_seeds": int(np.sum(np.asarray(w.elections) == 0)),
        "overflow_seeds": int(np.sum(np.asarray(final.overflow))),
        "events_total": int(np.sum(np.asarray(final.ctr))),
        "sim_ns_total": int(np.sum(np.asarray(final.now_ns))),
        "msgs_delivered": int(np.sum(np.asarray(w.msgs_delivered))),
    }
