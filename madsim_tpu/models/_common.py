"""Shared emit-packing helpers for device workload models.

Every model hands the engine a fixed-shape ``Emits`` batch per handler
invocation: ``num_nodes`` broadcast slots (one potential message per
destination node) followed by two "extra" slots (timer re-arms, unicast
replies). These helpers own that packing protocol in one place so the
models stay in sync with the engine's ``Emits`` contract.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.core import Emits

# sentinel for an unused extra slot
DISABLED = None

# sweep_summary keys that merge by max, not sum, across chunks — owned
# here so every model's summary and every cross-chunk reducer agree
MAX_KEYS = frozenset({"queue_high_water"})

# keys that merge by elementwise bitwise-OR (coverage bitmaps: a bit is
# covered by the sweep iff any chunk covered it)
OR_KEYS = frozenset({"coverage_map"})

# keys that merge by elementwise ADD (fixed-width count vectors: the
# engine's event-mix kind histogram sums across chunks, not concatenates)
VEC_KEYS = frozenset({"event_mix"})


def merge_summaries(totals: dict, summary: dict) -> dict:
    """Fold one chunk's ``sweep_summary`` dict into a running total.

    Keys are additive counts except ``MAX_KEYS`` (high-water marks),
    ``OR_KEYS`` (bitmap words, elementwise OR), ``VEC_KEYS`` (count
    vectors, elementwise add), and list values (concatenated — e.g.
    per-chunk violating-seed samples). Mutates and returns ``totals``
    (start with ``{}``)."""
    for k, v in summary.items():
        if k in MAX_KEYS:
            totals[k] = max(totals.get(k, 0), v)
        elif k in VEC_KEYS:
            old = totals.get(k, [])
            if len(old) < len(v):
                old = old + [0] * (len(v) - len(old))
            totals[k] = [
                a + b for a, b in zip(old, list(v) + [0] * (len(old) - len(v)))
            ]
        elif k in OR_KEYS:
            old = totals.get(k, [])
            if len(old) < len(v):
                old = old + [0] * (len(v) - len(old))
            totals[k] = [a | b for a, b in zip(old, list(v) + [0] * (len(old) - len(v)))]
        elif isinstance(v, list):
            totals[k] = totals.get(k, []) + v
        else:
            totals[k] = totals.get(k, 0) + v
    return totals


def coverage_bit_count(coverage_map) -> int:
    """Population count of a ``coverage_map`` word list (covered bits)."""
    return sum(int(w).bit_count() for w in coverage_map)


def memoized_workload(cfg_cls):
    """Decorator for a model's ``workload(cfg)`` constructor: memoize per
    config (configs are hashable NamedTuples), normalizing an omitted
    argument to ``cfg_cls()`` BEFORE the cache so ``workload()`` and
    ``workload(cfg_cls())`` share one entry.

    Why: the engine's jit caches (engine/core.py ``_drive`` static args)
    key on the Workload's ``partial``s by identity, so an equal-but-
    distinct Workload silently recompiles the whole sweep program
    (~16 s). Same config -> same Workload object -> cache hit."""
    from functools import lru_cache, wraps

    def deco(build):
        cached = lru_cache(maxsize=None)(build)

        @wraps(build)
        def workload(cfg=None):
            return cached(cfg if cfg is not None else cfg_cls())

        return workload

    return deco


def make_sweep_summary(
    fields: Tuple[Tuple[str, Callable], ...]
) -> Callable[[object], dict]:
    """Build a ``sweep_summary(final) -> dict`` from ``(name, lane_fn)``
    pairs, where each ``lane_fn(final)`` returns a PER-LANE vector
    ``[S]`` over the batched EngineState; the reduction (sum, or max
    for ``MAX_KEYS`` names) is owned here. Per-lane on purpose: it lets
    the ``limit=`` variant mask padded lanes out of every field
    EXACTLY — a zeroed lane is the identity of sum, of max over the
    nonnegative fields, and of the coverage OR, whereas predicate
    fields like raft's ``elections == 0`` would miscount zeroed lanes
    if masking happened below the field function.

    All reductions run in ONE jitted device program that stacks the
    scalars into a single int64 vector, so the whole summary costs one
    small device->host transfer. The eager alternative — one
    ``np.asarray`` per field — moves each full per-lane array to host
    and pays a round-trip per field, which dominates chunked pod-scale
    sweeps on a tunneled device (~0.9 s/chunk at 12 fields x 16k lanes)."""
    # EngineState-level per-lane fields shared by every model, appended
    # here so a new model (or engine counter) can't silently drop them
    engine_fields = (
        ("overflow_seeds", lambda f: f.overflow),
        ("hist_overflow_seeds", lambda f: f.hist_overflow),
        ("queue_high_water", lambda f: f.qmax),
        ("events_total", lambda f: f.ctr),
        ("sim_ns_total", lambda f: f.now_ns),
    )
    fields = fields + engine_fields
    names = tuple(n for n, _ in fields)
    fns = tuple(f for _, f in fields)

    def _reduce(final, m):
        cols = []
        for name, fn in zip(names, fns):
            lanes = jnp.asarray(fn(final), jnp.int64)
            if lanes.ndim != 1:
                # catch the pre-round-6 contract at trace time: a field
                # written as a scalar reduction (lambda f: jnp.sum(...))
                # would survive whole-chunk summaries but silently
                # multiply by the lane count under the limit mask
                raise ValueError(
                    f"sweep_summary field {name!r} must return a "
                    f"PER-LANE vector [S], got shape {lanes.shape} — "
                    "drop the jnp.sum/jnp.max: the reduction is owned "
                    "by make_sweep_summary (docs/authoring_models.md)"
                )
            if m is not None:
                lanes = jnp.where(m, lanes, jnp.int64(0))
            cols.append(
                jnp.max(lanes) if name in MAX_KEYS else jnp.sum(lanes)
            )
        # coverage union rides in the same program/transfer: OR the
        # per-seed bitmaps down the batch axis — the "one extra
        # reduction" that turns the engine's in-loop signal into a
        # chunk-level coverage map (explore/campaign.py feeds on it).
        # NOT lax.reduce with a bitwise_or combiner: when the batch axis
        # is sharded over a mesh (parallel/mesh.py), GSPMD turns the
        # lane reduction into a cross-device all-reduce, and the CPU
        # runtime only implements the stock combiners (add/min/max) for
        # it — so the OR is decomposed into 32 bit-planes reduced by
        # MAX (identical words: the planes are disjoint, so the
        # recombining sum IS the or), which partitions on every backend.
        cover = final.cover
        if m is not None:
            cover = jnp.where(m[:, None], cover, jnp.uint32(0))
        shifts = jnp.arange(32, dtype=jnp.uint32)
        bits = (cover[:, :, None] >> shifts) & jnp.uint32(1)  # [S, W, 32]
        union = jnp.sum(jnp.max(bits, axis=0) << shifts, axis=1,
                        dtype=jnp.uint32)
        # the opt-in event-mix plane rides along too: per-seed per-kind
        # uint32 counters summed down the batch axis to one [K] vector
        # (width 0 when the workload doesn't enable it — free)
        emix = final.evmix
        if m is not None:
            emix = jnp.where(m[:, None], emix, jnp.uint32(0))
        emix = jnp.sum(emix.astype(jnp.int64), axis=0)
        return jnp.stack(cols), union, emix

    _summarize = jax.jit(lambda final: _reduce(final, None))

    @jax.jit
    def _summarize_limit(final, k):
        # mask the padded lanes instead of slicing: one compiled
        # program serves EVERY ragged tail length, where a [k]-shaped
        # trim would recompile per distinct k
        return _reduce(final, jnp.arange(final.seed.shape[0]) < k)

    def sweep_summary(final, limit=None) -> dict:
        """Reduction of a finished sweep's batched EngineState (one
        device program, one transfer). ``limit=k`` reduces only the
        first ``k`` lanes — the padded-ragged-chunk path: the masked
        variant is ONE compiled program for all ``k``, so a ragged
        final chunk costs no recompile (engine/checkpoint.py drivers
        and scripts/sweep_million.py rely on this)."""
        if limit is None:
            vec, union, emix = _summarize(final)
            seeds = int(final.seed.shape[0])
        else:
            vec, union, emix = _summarize_limit(
                final, jnp.asarray(limit, jnp.int32)
            )
            seeds = int(limit)
        vec = np.asarray(vec)
        out = {"seeds": seeds}
        out.update((n, int(v)) for n, v in zip(names, vec))
        if union.shape[0]:
            out["coverage_map"] = [int(w) for w in np.asarray(union)]
        if emix.shape[0]:
            out["event_mix"] = [int(v) for v in np.asarray(emix)]
        return out

    # the chunk drivers key program-reuse decisions on this marker
    sweep_summary.supports_limit = True
    return sweep_summary

ExtraSlot = Optional[Tuple]  # (time, kind, pay, enable) or DISABLED


def pay(*vals, slots: int) -> jnp.ndarray:
    """Pack scalar values into an int32 payload vector of ``slots`` width."""
    out = jnp.zeros((slots,), jnp.int32)
    for i, v in enumerate(vals):
        out = out.at[i].set(jnp.asarray(v, jnp.int32))
    return out


def no_bcast(num_nodes: int, payload_slots: int, msg_kind: int):
    """An all-disabled broadcast block (still shaped [num_nodes])."""
    return (
        jnp.zeros((num_nodes,), jnp.int64),
        jnp.full((num_nodes,), msg_kind, jnp.int32),
        jnp.zeros((num_nodes, payload_slots), jnp.int32),
        jnp.zeros((num_nodes,), bool),
    )


def pack_extras(payload_slots: int, *extras: ExtraSlot) -> Emits:
    """Pack standalone slots into an ``Emits`` of exactly ``len(extras)``
    events. Each slot is ``(time, kind, pay, enable)`` or ``DISABLED``."""
    ets, eks, eps, eos = [], [], [], []
    for extra in extras:
        if extra is None:
            ets.append(jnp.zeros((), jnp.int64))
            eks.append(jnp.zeros((), jnp.int32))
            eps.append(jnp.zeros((payload_slots,), jnp.int32))
            eos.append(jnp.zeros((), bool))
        else:
            et, ek, ep, eo = extra
            ets.append(jnp.asarray(et, jnp.int64))
            eks.append(jnp.asarray(ek, jnp.int32))
            eps.append(ep)
            eos.append(jnp.asarray(eo, bool))
    return Emits(
        times=jnp.stack(ets),
        kinds=jnp.stack(eks),
        pays=jnp.stack(eps),
        enables=jnp.stack(eos),
    )


def pack_emits(payload_slots: int, bcast, *extras: ExtraSlot) -> Emits:
    """Pack ``num_nodes`` broadcast slots + 2 extra slots into ``Emits``.

    Each extra is ``(time, kind, pay, enable)`` or ``DISABLED``; every
    handler emits the same fixed shape (num_nodes + 2 events). One
    concatenate per field — no per-extra chains."""
    times, kinds, pays, enables = bcast
    assert len(extras) == 2
    ex = pack_extras(payload_slots, *extras)
    return Emits(
        times=jnp.concatenate([times, ex.times]),
        kinds=jnp.concatenate([kinds, ex.kinds]),
        pays=jnp.concatenate([pays, ex.pays]),
        enables=jnp.concatenate([enables, ex.enables]),
    )
