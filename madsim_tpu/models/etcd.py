"""etcd KV + lease service as a device workload — BASELINE config #2.

A 3-node cluster (1 etcd server + 2 clients) — revisioned KV store with
leases, client keepalive chains, and lease-expiry key deletion — with
network-partition fault injection, expressed as pure array handlers so
thousands of seeds run in lockstep on TPU. Third device model after Raft
and Kafka: request/response against a stateful service, faults on the
client links rather than the server process.

Behavior modeled from the reference etcd sim
(madsim-etcd-client/src/service.rs:189-485): ``ServiceInner { revision,
kv, lease }`` — every mutation bumps the revision (service.rs put/delete
paths), leases carry a TTL and an expiry task deletes attached keys when
the TTL lapses without a keepalive (service.rs:27-33,466-485), and
keepalives reset the countdown. Partition injection plays the role of the
reference's ``clog_node`` (madsim/src/sim/net/mod.rs:163-203): a clogged
client can't refresh its lease, so the server expires it — the classic
etcd session-loss scenario.

Online invariant checkers (any breach latches ``violation``):
- **revision monotonicity**: every server reply carries the current
  revision; a client observing a smaller revision than it has already
  seen is a violation (single serializable server — the etcd guarantee).
  The static ``bug_rev_regress`` flag makes lease expiry *decrement* the
  revision, which this checker catches from the client side.
- **lease-expiry correctness**: a GET must never observe a key whose
  attached lease expired more than a grace margin ago (the margin absorbs
  the engine's 50-100 ns dispatch jitter; the expiry event itself fires
  exactly at the deadline). The static ``bug_skip_expiry`` flag makes the
  expiry handler a no-op — expired keys linger and the checker catches
  the first stale GET.

Design notes:
- Lease staleness uses generation counters (``lease_gen``): each
  grant/keepalive bumps the generation and schedules a fresh K_EXPIRE at
  the new deadline; stale expiry timers are pay-mismatch drops (same
  pattern as models/raft.py timer chains).
- A keepalive for a lease that is not live (re)grants it — clients own a
  fixed lease slot and heartbeat it, the etcd-session usage pattern.
- Partition windows come from the shared fault compiler
  (``engine/faults.py``) and are refcounted per victim PER DIRECTION
  (``FaultState.part_in_cnt``/``part_out_cnt``); the clog matrix is
  derived from the refcounts, so overlapping windows — same victim,
  different victims sharing a link cell, symmetric over asymmetric —
  all compose exactly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

from ..engine import faults as efaults
from ..engine import net as enet
from ..engine.core import Emits, EngineConfig, Workload
from ..engine.ops import get1, get2, set1, set2
from ..engine.rng import bounded, prob_to_q32
from ..oracle.history import OP_GET, OP_PUT, PH_INVOKE, PH_OK
from . import _common
from ._common import pack_extras, pay as _mkpay

# event kinds
K_OP = 0  # pay = (client,) — client op timer: send a PUT or GET
K_KEEPALIVE = 1  # pay = (client,) — client lease-heartbeat timer
K_MSG = 2  # pay = (dst, mtype, src, a, b, c, opid)
K_EXPIRE = 3  # pay = (lease, gen) — server lease-expiry deadline
K_FAULT = 4  # pay = (action, victim, t_lo, t_hi) — engine/faults.py stream

# message types (slot 6 is the history opid on KV requests/replies, -1
# on lease traffic — the oracle's completion records key on it)
MT_LEASE = 0  # grant-or-keepalive; a = lease id
MT_PUT = 1  # a = key, b = val, c = lease id (-1 = none)
MT_GET = 2  # a = key
MT_RSP = 3  # a = revision, b = per-client reply sequence number, c = op
#             result (PUT: the value written; GET: value read or -1 =
#             absent) — replies are independent datagrams here, but etcd
#             clients read ordered responses off one gRPC stream, so the
#             monotonicity check orders replies by the server-assigned
#             sequence (reordered arrivals are stale and skipped, never
#             mis-flagged)

PAYLOAD_SLOTS = 7
SERVER = 0

# violation flavors (bitmask latched in ``viol_kind``; ``violation`` stays
# the any-flavor bool). The explore subsystem's triage keys on these.
V_REV = 1  # a client observed the revision going backwards
V_EXPIRY = 2  # a GET observed a key whose lease expired long ago

# pending-op table depth per client (in-flight KV ops awaiting replies,
# matched by opid; a slot collision just leaves the older op open in the
# recorded history — sound, the checker treats open ops as optional)
PEND = 8


class EtcdConfig(NamedTuple):
    """Static sweep parameters (hashable — part of the jit key)."""

    num_clients: int = 2
    num_keys: int = 8
    ttl_ns: int = 1_000_000_000
    # client cadences
    keepalive_lo_ns: int = 200_000_000
    keepalive_hi_ns: int = 400_000_000
    op_lo_ns: int = 50_000_000
    op_hi_ns: int = 150_000_000
    # legacy client-partition shorthand, compiled through engine/faults.py;
    # `faults` (below) overrides all four when set
    partitions: int = 2
    part_window_ns: int = 3_000_000_000
    part_lo_ns: int = 500_000_000
    part_hi_ns: int = 2_000_000_000
    # expiry-check grace: absorbs dispatch jitter (≫ 100 ns, ≪ ttl)
    grace_ns: int = 1_000_000
    # network model
    loss_q32: int = prob_to_q32(0.01)
    lat_lo_ns: int = 1_000_000
    lat_hi_ns: int = 10_000_000
    buggify_q32: int = 0
    # deliberate bugs for checker validation
    bug_skip_expiry: bool = False  # expiry handler does nothing
    bug_rev_regress: bool = False  # expiry decrements the revision
    # GETs serve the key's value as of BEFORE its latest mutation — the
    # classic stale-read bug. Invisible to the online checkers (revision
    # and lease bookkeeping stay intact); the history oracle
    # (madsim_tpu/oracle) catches it as a linearizability breach.
    bug_stale_read: bool = False
    # operation-history buffer rows per seed (madsim_tpu/oracle); 0 =
    # recording off. Ops on the non-lease keys [num_clients, num_keys)
    # are recorded (lease keys are mutated by server-internal expiry,
    # which has no client-observed invoke/complete to record).
    hist_slots: int = 0
    # full declarative fault campaign (engine/faults.FaultSpec), a
    # literal schedule, or a FaultEnvelope (spec-as-data: the concrete
    # candidate rides in as per-lane FaultParams); None = derive a
    # client-partition spec from the legacy fields above
    faults: Optional[
        Union[efaults.FaultSpec, efaults.FixedFaults, efaults.FaultEnvelope]
    ] = None

    @property
    def num_nodes(self) -> int:
        return 1 + self.num_clients


def fault_spec(cfg: EtcdConfig) -> efaults.FaultSpec:
    """``cfg.faults`` verbatim, or the legacy partition fields lifted into
    a FaultSpec whose partition group is the client nodes (1..N)."""
    if cfg.faults is not None:
        return cfg.faults
    return efaults.FaultSpec(
        partitions=cfg.partitions,
        part_window_ns=cfg.part_window_ns,
        part_lo_ns=cfg.part_lo_ns,
        part_hi_ns=cfg.part_hi_ns,
        part_group=(1, -1),
    )


def _rt(cfg: EtcdConfig, w: "EtcdState"):
    """Runtime spec view for the in-loop interpreter: the static spec on
    the legacy path, this lane's traced ``FaultRt`` on the envelope path."""
    return efaults.runtime_spec(fault_spec(cfg), w.frt)


class EtcdState(NamedTuple):
    # server KV [K]
    kv_present: jnp.ndarray  # bool
    kv_val: jnp.ndarray  # int32
    kv_mod_rev: jnp.ndarray  # int32
    kv_lease: jnp.ndarray  # int32 (-1 = none)
    # pre-mutation shadow of each key (bug_stale_read serves from these)
    kv_prev_present: jnp.ndarray  # bool
    kv_prev_val: jnp.ndarray  # int32
    rev: jnp.ndarray  # int32 server revision
    # leases [NC] (one slot per client)
    lease_on: jnp.ndarray  # bool
    lease_exp: jnp.ndarray  # int64
    lease_gen: jnp.ndarray  # int32
    # server-side per-client reply sequence [NC]
    rsp_seq: jnp.ndarray  # int32 replies sent to this client so far
    # clients [NC]
    seen_rev: jnp.ndarray  # int32 revision of the newest-sequenced reply
    seen_seq: jnp.ndarray  # int32 sequence number of that reply
    # client op-history bookkeeping (madsim_tpu/oracle): opid allocator
    # [NC] plus the pending-op table [NC, PEND] the completion record
    # reads its (op, key, input) back out of, matched by opid
    next_opid: jnp.ndarray  # int32[NC]
    pend_id: jnp.ndarray  # int32[NC, PEND] opid in this slot (-1 = free)
    pend_op: jnp.ndarray  # int32[NC, PEND] OP_PUT / OP_GET
    pend_key: jnp.ndarray  # int32[NC, PEND]
    pend_val: jnp.ndarray  # int32[NC, PEND] PUT value (0 for GET)
    # shared liveness/pause/partition/burst state [num_nodes]
    fstate: efaults.FaultState
    # network
    links: enet.LinkState
    # sweep outputs
    violation: jnp.ndarray  # bool
    viol_kind: jnp.ndarray  # int32 flavor bitmask (V_REV | V_EXPIRY)
    vio_rev: jnp.ndarray  # bool (revision went backwards)
    vio_expiry: jnp.ndarray  # bool (GET saw an expired-lease key)
    puts: jnp.ndarray  # int32
    gets: jnp.ndarray  # int32
    keepalives: jnp.ndarray  # int32 (server-processed)
    grants: jnp.ndarray  # int32 (keepalives that (re)granted)
    expiries: jnp.ndarray  # int32 (leases actually expired)
    keys_expired: jnp.ndarray  # int32 (keys deleted by expiry)
    parts: jnp.ndarray  # int32 partitions applied
    msgs_sent: jnp.ndarray  # int32
    msgs_delivered: jnp.ndarray  # int32
    # spec-as-data (engine/faults.py): this lane's runtime override
    # scalars (FaultRt) on the envelope path; a leafless () on the legacy
    # path
    frt: object


def _pay(*vals) -> jnp.ndarray:
    return _mkpay(*vals, slots=PAYLOAD_SLOTS)


def _emits2(slot1, slot2) -> Emits:
    """Two-slot Emits (this model never broadcasts); each slot is
    ``(time, kind, pay, enable)`` or None."""
    return pack_extras(PAYLOAD_SLOTS, slot1, slot2)


def _client_node(c):
    return jnp.asarray(c, jnp.int32) + 1


# -- event handlers ----------------------------------------------------------


def _on_op_timer(cfg: EtcdConfig, w: EtcdState, now, pay, rand):
    """Client c sends a PUT (own key, lease-attached; or a shared key,
    no lease) or a GET of a random key, then re-arms. A crashed/paused
    client's timer keeps ticking but sends nothing (the kafka model's
    timer idiom — host tier: the killed node's tasks are gone)."""
    c = pay[0]
    node = _client_node(c)
    can_send = get1(efaults.up(w.fstate), node)
    t, deliver = enet.route(w.links, now, node, SERVER, rand[0], rand[1])
    kind_draw = rand[2]
    key_draw = bounded(rand[3], 0, cfg.num_keys).astype(jnp.int32)
    is_put = (kind_draw & 1) == 0
    # PUTs alternate between the client's lease key (key id == client id,
    # lease attached) and a shared key (no lease)
    own_key = (kind_draw & 2) == 0
    put_key = jnp.where(own_key, c, key_draw)
    put_lease = jnp.where(own_key, c, jnp.int32(-1))
    val = (rand[4] >> 1).astype(jnp.int32)
    # history bookkeeping: every request that actually enters the network
    # claims the client's next opid and parks (op, key, input) in the
    # pending table; the reply echoes the opid so the completion record
    # can read the invocation back out (madsim_tpu/oracle)
    sent = can_send & deliver
    opid = get1(w.next_opid, c)
    slot = opid % PEND
    op_code = jnp.where(is_put, jnp.int32(OP_PUT), jnp.int32(OP_GET))
    op_key = jnp.where(is_put, put_key, key_draw)
    op_val = jnp.where(is_put, val, jnp.int32(0))
    msg = jnp.where(
        is_put,
        _pay(SERVER, MT_PUT, node, put_key, val, put_lease, opid),
        _pay(SERVER, MT_GET, node, key_draw, 0, 0, opid),
    )
    interval = efaults.skewed_delay(
        fault_spec(cfg), w.fstate, node,
        bounded(rand[5], cfg.op_lo_ns, cfg.op_hi_ns),
        rt=_rt(cfg, w),
    )
    emits = _emits2(
        (t, K_MSG, msg, sent),
        (now + interval, K_OP, _pay(c), True),
    )
    w2 = w._replace(
        next_opid=set1(w.next_opid, c, opid + 1, sent),
        pend_id=set2(w.pend_id, c, slot, opid, sent),
        pend_op=set2(w.pend_op, c, slot, op_code, sent),
        pend_key=set2(w.pend_key, c, slot, op_key, sent),
        pend_val=set2(w.pend_val, c, slot, op_val, sent),
        msgs_sent=w.msgs_sent + jnp.where(can_send, 1, 0),
        msgs_delivered=w.msgs_delivered + jnp.where(sent, 1, 0),
    )
    return w2, emits


def _on_keepalive_timer(cfg: EtcdConfig, w: EtcdState, now, pay, rand):
    """Client c heartbeats its lease and re-arms; a crashed/paused
    client sends nothing, so its lease genuinely expires — the checker
    coverage client death exists to exercise."""
    c = pay[0]
    node = _client_node(c)
    can_send = get1(efaults.up(w.fstate), node)
    t, deliver = enet.route(w.links, now, node, SERVER, rand[0], rand[1])
    interval = efaults.skewed_delay(
        fault_spec(cfg), w.fstate, node,
        bounded(rand[2], cfg.keepalive_lo_ns, cfg.keepalive_hi_ns),
        rt=_rt(cfg, w),
    )
    # opid -1: lease traffic carries no history opid, so its reply can
    # never alias a pending KV op's completion record
    emits = _emits2(
        (t, K_MSG, _pay(SERVER, MT_LEASE, node, c, 0, 0, -1), can_send & deliver),
        (now + interval, K_KEEPALIVE, _pay(c), True),
    )
    w2 = w._replace(
        msgs_sent=w.msgs_sent + jnp.where(can_send, 1, 0),
        msgs_delivered=w.msgs_delivered + jnp.where(can_send & deliver, 1, 0),
    )
    return w2, emits


def _on_msg(cfg: EtcdConfig, w: EtcdState, now, pay, rand):
    dst, mtype, src, a, b, c_ = pay[0], pay[1], pay[2], pay[3], pay[4], pay[5]
    opid = pay[6]
    up = efaults.up(w.fstate)
    at_server = (dst == SERVER) & get1(up, SERVER)

    # -- server: LEASE (grant-or-keepalive) — reset the countdown, bump the
    # generation, schedule a fresh expiry deadline (service.rs keepalive +
    # the per-second expiry tick collapsed to an exact-deadline event)
    is_lease = at_server & (mtype == MT_LEASE)
    lease = a
    was_on = get1(w.lease_on, lease)
    new_gen = get1(w.lease_gen, lease) + 1
    # the expiry deadline is a SERVER timer: a skewed server clock
    # stretches the TTL countdown (keys linger — the gray failure)
    new_exp = now + efaults.skewed_delay(
        fault_spec(cfg), w.fstate, jnp.int32(SERVER), cfg.ttl_ns,
        rt=_rt(cfg, w),
    )
    lease_on2 = set1(w.lease_on, lease, True, is_lease)
    lease_exp2 = set1(w.lease_exp, lease, new_exp, is_lease)
    lease_gen2 = set1(w.lease_gen, lease, new_gen, is_lease)

    # -- server: PUT — one revision per mutation (service.rs put path).
    # A PUT attaching a lease that is not live is rejected, as in etcd
    # (grant must precede attach): without this, a client whose op timer
    # beats its first keepalive would create a key with a dead lease.
    is_put = at_server & (mtype == MT_PUT)
    key, val, put_lease = a, b, c_
    safe_put_lease = jnp.clip(put_lease, 0, cfg.num_clients - 1)
    lease_live = (put_lease < 0) | get1(lease_on2, safe_put_lease)
    do_put = is_put & lease_live
    rev2 = jnp.where(do_put, w.rev + 1, w.rev)
    # shadow the pre-mutation value BEFORE overwriting (bug_stale_read
    # serves GETs from this snapshot)
    kv_prev_present2 = set1(w.kv_prev_present, key, get1(w.kv_present, key), do_put)
    kv_prev_val2 = set1(w.kv_prev_val, key, get1(w.kv_val, key), do_put)
    kv_present2 = set1(w.kv_present, key, True, do_put)
    kv_val2 = set1(w.kv_val, key, val, do_put)
    kv_mod_rev2 = set1(w.kv_mod_rev, key, rev2, do_put)
    kv_lease2 = set1(w.kv_lease, key, put_lease, do_put)

    # -- server: GET — THE expiry checker moment: the key must not carry a
    # lease that expired more than grace_ns ago (the expiry event fires at
    # the deadline; grace absorbs dispatch jitter)
    is_get = at_server & (mtype == MT_GET)
    g_present = get1(kv_present2, a)
    g_lease = get1(kv_lease2, a)
    has_lease = g_lease >= 0
    safe_lease = jnp.clip(g_lease, 0, cfg.num_clients - 1)
    g_exp = get1(lease_exp2, safe_lease)
    g_on = get1(lease_on2, safe_lease)
    stale = (
        is_get
        & g_present
        & has_lease
        & (~g_on | (g_exp + cfg.grace_ns < now))
    )

    # -- client: RSP — revision monotonicity, checked in server-send
    # order (replies reordered by the network are stale and skipped, as a
    # real client reading one ordered gRPC stream would never see them)
    is_rsp = (mtype == MT_RSP) & (dst >= 1) & get1(up, dst)
    client = dst - 1
    newer = is_rsp & (b > get1(w.seen_seq, client))
    regress = newer & (a < get1(w.seen_rev, client))
    seen2 = set1(w.seen_rev, client, a, newer)
    seen_seq2 = set1(w.seen_seq, client, b, newer)

    # the served value: what this GET tells its client. The stale-read
    # bug swaps in the pre-mutation shadow — revision and lease
    # bookkeeping stay intact, so only the history oracle can see it.
    g_val = jnp.where(g_present, get1(kv_val2, a), jnp.int32(-1))
    if cfg.bug_stale_read:
        g_val = jnp.where(
            get1(kv_prev_present2, a), get1(kv_prev_val2, a), jnp.int32(-1)
        )

    # server replies to every request, stamped with the current revision
    # and the per-client sequence number that orders the client-side check
    rt, rdeliver = enet.route(w.links, now, SERVER, src, rand[0], rand[1])
    is_req = is_lease | is_put | is_get
    req_client = jnp.clip(src - 1, 0, cfg.num_clients - 1)
    next_seq = get1(w.rsp_seq, req_client) + 1
    rsp_seq2 = set1(w.rsp_seq, req_client, next_seq, is_req)
    result = jnp.where(is_get, g_val, jnp.where(is_put, val, jnp.int32(0)))
    reply_opid = jnp.where(is_put | is_get, opid, jnp.int32(-1))
    reply = _pay(src, MT_RSP, SERVER, rev2, next_seq, result, reply_opid)
    # fresh expiry deadline for a (re)granted/refreshed lease
    emits = _emits2(
        (rt, K_MSG, reply, is_req & rdeliver),
        (new_exp, K_EXPIRE, _pay(lease, new_gen), is_lease),
    )
    w2 = w._replace(
        lease_on=lease_on2,
        lease_exp=lease_exp2,
        lease_gen=lease_gen2,
        rev=rev2,
        kv_present=kv_present2,
        kv_val=kv_val2,
        kv_mod_rev=kv_mod_rev2,
        kv_lease=kv_lease2,
        kv_prev_present=kv_prev_present2,
        kv_prev_val=kv_prev_val2,
        rsp_seq=rsp_seq2,
        seen_rev=seen2,
        seen_seq=seen_seq2,
        vio_expiry=w.vio_expiry | stale,
        vio_rev=w.vio_rev | regress,
        violation=w.violation | stale | regress,
        viol_kind=w.viol_kind
        | jnp.where(stale, jnp.int32(V_EXPIRY), jnp.int32(0))
        | jnp.where(regress, jnp.int32(V_REV), jnp.int32(0)),
        puts=w.puts + jnp.where(do_put, 1, 0),
        gets=w.gets + jnp.where(is_get, 1, 0),
        keepalives=w.keepalives + jnp.where(is_lease, 1, 0),
        grants=w.grants + jnp.where(is_lease & ~was_on, 1, 0),
        msgs_sent=w.msgs_sent + jnp.where(is_req, 1, 0),
        msgs_delivered=w.msgs_delivered + jnp.where(is_req & rdeliver, 1, 0),
    )
    return w2, emits


def _on_expire(cfg: EtcdConfig, w: EtcdState, now, pay, rand):
    """Lease-expiry deadline: if the generation still matches (no keepalive
    arrived since), drop the lease and delete every attached key
    (service.rs:466-485)."""
    lease, gen = pay[0], pay[1]
    valid = get1(w.lease_on, lease) & (gen == get1(w.lease_gen, lease))
    if cfg.bug_skip_expiry:
        valid = jnp.zeros((), bool)
    attached = w.kv_present & (w.kv_lease == lease)
    n_del = jnp.sum(attached & valid, dtype=jnp.int32)
    # one revision per expiry batch (the reference's expiry txn)
    if cfg.bug_rev_regress:
        rev2 = jnp.where(valid & (n_del > 0), w.rev - 1, w.rev)
    else:
        rev2 = jnp.where(valid & (n_del > 0), w.rev + 1, w.rev)
    w2 = w._replace(
        lease_on=set1(w.lease_on, lease, False, valid),
        kv_present=w.kv_present & ~(attached & valid),
        rev=rev2,
        expiries=w.expiries + jnp.where(valid, 1, 0),
        keys_expired=w.keys_expired + n_del,
    )
    return w2, _emits2(None, None)


def _on_fault(cfg: EtcdConfig, w: EtcdState, now, pay, rand):
    """One event of the compiled fault campaign (engine/faults.py): the
    shared interpreter handles the refcounted clog/heal (overlapping
    windows of the same victim compose — the heal of the first window
    must not reopen the second's), liveness/pause masks, and latency/loss
    bursts. This model has no per-node volatile state to reset: faults
    here act on connectivity and processing gates only (the server's KV
    store is durable; lease expiry deadlines keep running through a
    server crash/pause window)."""
    action, victim = pay[0], pay[1]
    base = efaults.NetBase(cfg.lat_lo_ns, cfg.lat_hi_ns, cfg.loss_q32)
    links2, f2, _edges = efaults.on_event(
        _rt(cfg, w), base, w.links, w.fstate, action, victim
    )
    part_like = (
        (action == efaults.F_PART)
        | (action == efaults.F_PART_IN)
        | (action == efaults.F_PART_OUT)
    )
    w2 = w._replace(
        links=links2,
        fstate=f2,
        parts=w.parts + jnp.where(part_like, 1, 0),
    )
    return w2, _emits2(None, None)


def _handle(cfg: EtcdConfig, w: EtcdState, now, kind, pay, rand):
    branches = [
        partial(_on_op_timer, cfg),
        partial(_on_keepalive_timer, cfg),
        partial(_on_msg, cfg),
        partial(_on_expire, cfg),
        partial(_on_fault, cfg),
    ]
    return jax.lax.switch(kind, branches, w, now, pay, rand)


def _probe(w: EtcdState):
    """Violation-flavor bitmask (engine contract: ``Workload.probe``) —
    recorded per step by ``run_traced`` so triage can locate the first
    violating event."""
    return w.viol_kind


N_KINDS = 5  # K_OP..K_FAULT


def cover_bits(cfg: EtcdConfig) -> int:
    """Size of the coverage bitmap: one bit per (event kind, node,
    facet) plus one bit per violation flavor. The facet is the message
    type for K_MSG and the fault action for K_FAULT (the two kinds with
    interesting substructure), 0 otherwise."""
    return N_KINDS * cfg.num_nodes * 4 + 2


def _cover(cfg: EtcdConfig, wb: EtcdState, wa: EtcdState, now, kind, pay):
    """Map one dispatched event to its coverage bit (engine contract:
    ``Workload.cover``) — the swarm-testing signal the explore loop's
    retention and the steering bandit (explore/steer.py) feed on. A
    newly latched violation flavor claims the event's bit instead,
    mirroring models/raft.py (flavor bits are the rarest coverage)."""
    node = jnp.where(kind == K_FAULT, pay[1], pay[0])
    node = jnp.clip(node, 0, cfg.num_nodes - 1)
    facet = jnp.where(
        kind == K_MSG,
        jnp.clip(pay[1], 0, 3),
        jnp.where(kind == K_FAULT, jnp.clip(pay[0], 0, 3), 0),
    )
    bit = (kind * cfg.num_nodes + node) * 4 + facet
    base = N_KINDS * cfg.num_nodes * 4
    new_viol = wa.viol_kind & ~wb.viol_kind
    return jnp.where(
        new_viol != 0,
        base + jnp.where((new_viol & V_REV) != 0, 0, 1),
        bit,
    )


def _record(cfg: EtcdConfig, wb: EtcdState, wa: EtcdState, now, kind, pay):
    """Map one dispatched event to its op-history record (engine
    contract: ``Workload.record`` — at most ONE row per event).

    Two row sources, mutually exclusive by event kind: a K_OP timer that
    actually put a request on the wire writes the op's INVOKE row (the
    fields were just parked in the pending table), and a delivered
    MT_RSP whose echoed opid still matches its pending slot writes the
    OK row. Only ops on the non-lease keys [num_clients, num_keys) are
    recorded: lease keys are mutated by server-internal expiry, which no
    client observes, so their subhistories would be uncheckable."""
    nc = cfg.num_clients

    # invoke side: the op timer bumped this client's opid allocator
    c = jnp.clip(pay[0], 0, nc - 1)
    inv_opid = get1(wb.next_opid, c)
    sent = (kind == K_OP) & (get1(wa.next_opid, c) > inv_opid)
    slot = inv_opid % PEND
    inv_op = get2(wa.pend_op, c, slot)
    inv_key = get2(wa.pend_key, c, slot)
    inv_val = get2(wa.pend_val, c, slot)
    inv_en = sent & (inv_key >= nc)

    # completion side: a delivered KV reply matching its pending slot
    dst, mtype, result, opid = pay[0], pay[1], pay[5], pay[6]
    rc = jnp.clip(dst - 1, 0, nc - 1)
    is_rsp = (
        (kind == K_MSG)
        & (mtype == MT_RSP)
        & (dst >= 1)
        & get1(efaults.up(wb.fstate), jnp.clip(dst, 0, cfg.num_nodes - 1))
        & (opid >= 0)
    )
    rslot = jnp.clip(opid, 0, jnp.int32(2**30)) % PEND
    rsp_op = get2(wb.pend_op, rc, rslot)
    rsp_key = get2(wb.pend_key, rc, rslot)
    matched = is_rsp & (get2(wb.pend_id, rc, rslot) == opid)
    ok_en = matched & (rsp_key >= nc)

    def col(inv, ok):
        return jnp.where(inv_en, jnp.asarray(inv, jnp.int32), jnp.asarray(ok, jnp.int32))

    rec = jnp.stack(
        [
            col(c, rc),
            col(inv_op * 2 + PH_INVOKE, rsp_op * 2 + PH_OK),
            col(inv_key, rsp_key),
            col(inv_val, result),
            col(inv_opid, opid),
        ]
    )
    return rec, inv_en | ok_en


def _init(cfg: EtcdConfig, key, params=None):
    nc = cfg.num_clients
    if cfg.num_keys < nc:
        raise ValueError("num_keys must cover one lease key per client")
    ninit = 2 * nc
    rand = jax.random.bits(
        jax.random.fold_in(key, 0x7FFF_FFFF), (ninit,), dtype=jnp.uint32
    )
    w = EtcdState(
        kv_present=jnp.zeros((cfg.num_keys,), bool),
        kv_val=jnp.zeros((cfg.num_keys,), jnp.int32),
        kv_mod_rev=jnp.zeros((cfg.num_keys,), jnp.int32),
        kv_lease=jnp.full((cfg.num_keys,), -1, jnp.int32),
        kv_prev_present=jnp.zeros((cfg.num_keys,), bool),
        kv_prev_val=jnp.zeros((cfg.num_keys,), jnp.int32),
        rev=jnp.zeros((), jnp.int32),
        lease_on=jnp.zeros((nc,), bool),
        lease_exp=jnp.zeros((nc,), jnp.int64),
        lease_gen=jnp.zeros((nc,), jnp.int32),
        rsp_seq=jnp.zeros((nc,), jnp.int32),
        seen_rev=jnp.zeros((nc,), jnp.int32),
        seen_seq=jnp.zeros((nc,), jnp.int32),
        next_opid=jnp.zeros((nc,), jnp.int32),
        pend_id=jnp.full((nc, PEND), -1, jnp.int32),
        pend_op=jnp.zeros((nc, PEND), jnp.int32),
        pend_key=jnp.zeros((nc, PEND), jnp.int32),
        pend_val=jnp.zeros((nc, PEND), jnp.int32),
        fstate=efaults.init_state(cfg.num_nodes),
        links=enet.make(
            cfg.num_nodes, cfg.loss_q32, cfg.lat_lo_ns, cfg.lat_hi_ns,
            cfg.buggify_q32,
        ),
        violation=jnp.zeros((), bool),
        viol_kind=jnp.zeros((), jnp.int32),
        vio_rev=jnp.zeros((), bool),
        vio_expiry=jnp.zeros((), bool),
        puts=jnp.zeros((), jnp.int32),
        gets=jnp.zeros((), jnp.int32),
        keepalives=jnp.zeros((), jnp.int32),
        grants=jnp.zeros((), jnp.int32),
        expiries=jnp.zeros((), jnp.int32),
        keys_expired=jnp.zeros((), jnp.int32),
        parts=jnp.zeros((), jnp.int32),
        msgs_sent=jnp.zeros((), jnp.int32),
        msgs_delivered=jnp.zeros((), jnp.int32),
        frt=efaults.make_rt(fault_spec(cfg), params),
    )
    times = jnp.zeros((ninit,), jnp.int64)
    kinds = jnp.zeros((ninit,), jnp.int32)
    pays = jnp.zeros((ninit, PAYLOAD_SLOTS), jnp.int32)
    enables = jnp.ones((ninit,), bool)
    for c in range(nc):
        # keepalive chain starts early (first heartbeat grants the lease)
        times = times.at[2 * c].set(bounded(rand[2 * c], 0, 50_000_000))
        kinds = kinds.at[2 * c].set(K_KEEPALIVE)
        pays = pays.at[2 * c].set(_pay(c))
        times = times.at[2 * c + 1].set(
            bounded(rand[2 * c + 1], cfg.op_lo_ns, cfg.op_hi_ns)
        )
        kinds = kinds.at[2 * c + 1].set(K_OP)
        pays = pays.at[2 * c + 1].set(_pay(c))
    # fault campaign: the shared compiler's event stream, spliced in
    fe = efaults.compile_device(
        fault_spec(cfg), cfg.num_nodes, key, K_FAULT, PAYLOAD_SLOTS,
        params=params,
    )
    return w, Emits(
        times=jnp.concatenate([times, fe.times]),
        kinds=jnp.concatenate([kinds, fe.kinds]),
        pays=jnp.concatenate([pays, fe.pays]),
        enables=jnp.concatenate([enables, fe.enables]),
    )


def history_spec():
    """The sequential spec this model's recorded histories check
    against (oracle/specs.KVSpec) — also the key the device screen
    dispatches on (oracle/screen.screen_for), so a checked sweep needs
    no per-call-site spec plumbing."""
    from ..oracle.specs import KVSpec

    return KVSpec()


@_common.memoized_workload(EtcdConfig)
def workload(cfg: EtcdConfig = None) -> Workload:
    """Build the engine Workload for an etcd sweep configuration
    (memoized per config — see _common.memoized_workload)."""
    return Workload(
        init=partial(_init, cfg),
        handle=partial(_handle, cfg),
        num_rand=6,
        payload_slots=PAYLOAD_SLOTS,
        max_emits=2,
        probe=_probe,
        cover=partial(_cover, cfg),
        cover_bits=cover_bits(cfg),
        record=partial(_record, cfg) if cfg.hist_slots > 0 else None,
        hist_slots=cfg.hist_slots,
    )


def engine_config(cfg: EtcdConfig = EtcdConfig(), **overrides) -> EngineConfig:
    """Engine parameters: steady state holds 2 timer chains + ≤1 request +
    ≤1 reply per client, plus the expiry deadlines — every keepalive
    schedules a fresh K_EXPIRE while stale generations stay queued until
    their deadlines pass, so up to ``ceil(ttl / keepalive_lo) + 1``
    coexist per lease — and the partition plan."""
    stale_expiries = cfg.ttl_ns // cfg.keepalive_lo_ns + 1
    defaults = dict(
        queue_capacity=max(
            48,
            cfg.num_clients * (4 + stale_expiries)
            + efaults.num_events(fault_spec(cfg))
            + 8,
        ),
        time_limit_ns=5_000_000_000,
        max_steps=200_000,
    )
    defaults.update(overrides)
    return EngineConfig(**defaults)


# one jitted device program for the whole summary (one transfer) — see
# _common.make_sweep_summary
sweep_summary = _common.make_sweep_summary(
    (
        ("violations", lambda f: f.wstate.violation),
        ("rev_regress_seeds", lambda f: f.wstate.vio_rev),
        ("expiry_seeds", lambda f: f.wstate.vio_expiry),
        ("puts", lambda f: f.wstate.puts),
        ("gets", lambda f: f.wstate.gets),
        ("keepalives", lambda f: f.wstate.keepalives),
        ("grants", lambda f: f.wstate.grants),
        ("expiries", lambda f: f.wstate.expiries),
        ("keys_expired", lambda f: f.wstate.keys_expired),
        ("partitions", lambda f: f.wstate.parts),
        ("final_rev", lambda f: f.wstate.rev),
        ("msgs_sent", lambda f: f.wstate.msgs_sent),
        ("msgs_delivered", lambda f: f.wstate.msgs_delivered),
    )
)
