"""S3 object store as a device workload — the third ecosystem state machine.

A single S3 server plus ``num_clients`` clients, each driving a random
op mix — put_object / get_object / delete_object and the full multipart
lifecycle (create_multipart_upload → upload_part × P → complete) — with
retry-until-ack request delivery, server crash/restart fault injection,
and per-message loss/latency, expressed as pure array handlers so
thousands of seeds run in lockstep on TPU. Together with models/raft.py,
models/kafka.py, and models/etcd.py this completes the SURVEY §7 stage-6
workload tier: one substrate, four actor topologies.

Behavior modeled from the reference S3 service state machine
(madsim-aws-sdk-s3/src/server/service.rs:204-346 — per-(bucket,key)
objects; multipart parts staged per upload_id and assembled into the
object body only at complete_multipart_upload; an unknown upload_id is
NoSuchUpload) plus the crash/restart semantics the reference applies to
any node (madsim/src/sim/task/mod.rs:347-394). The durability contract
is S3's: a success response to put/complete promises the object survives
failures from that moment on. Crash semantics here: committed state
rolls back to the durable tier, and every staged (uncompleted) multipart
upload is aborted — its clients observe NoSuchUpload on their next part
and must restart the upload, exactly the reference's staged-parts model.

Online invariant checkers (any breach latches ``violation``):
- **acked-object durability**: at crash time, every object version the
  server has acknowledged (success response generated) must have a
  durable copy (``last_acked_ver <= ver_dur`` per key). The static
  ``bug_ack_before_durable`` flag defers durability to a periodic flush
  while still acking at processing time — the classic ack-before-durable
  bug — which this checker catches at a reported seed.
- **monotonic serve**: the version a GET serves for a key never
  regresses (a regression = a previously served write vanished). Holds
  structurally in correct mode (commit point == durability point); in
  bug mode a crash rolls committed state back and later GETs observe it.

Design notes (shared with models/kafka.py):
- All key/client indexing is one-hot masked (engine/ops.py) — no dynamic
  scatter/gather on the hot path.
- Timer staleness uses generation counters: ``sgen`` guards the server's
  flush-timer chain across crash/restart; multipart uploads are keyed by
  a server-issued ``gen`` so stale parts/completes from an aborted
  upload are rejected (NoSuchUpload), and a remembered ``done_gen`` makes
  complete_multipart_upload idempotent under response loss.
- Clients are self-clocked state machines: one re-arming op timer per
  client re-sends the in-flight request until its ack arrives
  (at-least-once; server-side idempotency via version bumps and the
  part bitmask).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

from ..engine import faults as efaults
from ..engine import net as enet
from ..engine.core import Emits, EngineConfig, Workload
from ..engine.ops import get1, set1
from ..engine.rng import bounded, prob_to_q32
from . import _common

# event kinds
K_OP = 0  # pay = (client,) — client timer: start or re-send current op
K_MSG = 1  # pay = (dst_node, mtype, src_node, a, b)
K_FLUSH = 2  # pay = (sgen,) — server durability timer (bug mode)
K_FAULT = 3  # pay = (action, victim, t_lo, t_hi) — engine/faults.py stream

# message types (pay slots a/b per type)
MT_PUT = 1  # a = key, b = len
MT_GET = 2  # a = key
MT_DEL = 3  # a = key
MT_CREATE = 4  # a = key
MT_PART = 5  # a = gen, b = part index
MT_COMPLETE = 6  # a = gen
MT_PUT_ACK = 7  # a = version
MT_GET_RSP = 8  # a = version, b = len (-1 = absent)
MT_DEL_ACK = 9  # a = version
MT_CREATE_ACK = 10  # a = gen
MT_PART_ACK = 11  # a = gen, b = part index
MT_COMP_ACK = 12  # a = gen
MT_ERR = 13  # a = gen — NoSuchUpload (service.rs:616-619)

# client phases
IDLE = 0
P_PUT = 1
P_GET = 2
P_DEL = 3
P_MPC = 4  # create_multipart_upload sent
P_MPP = 5  # uploading parts
P_MPX = 6  # complete_multipart_upload sent

PAYLOAD_SLOTS = 6
SERVER = 0  # node id of the S3 server


class S3Config(NamedTuple):
    """Static sweep parameters (hashable — part of the jit key)."""

    num_clients: int = 3
    num_keys: int = 4
    ops_per_client: int = 10
    # op mix (out of 8): 3 put, 2 get, 1 delete, 2 multipart
    parts_per_upload: int = 3
    part_len: int = 4  # every part is one fixed-size unit
    max_put_len: int = 4  # put_object length drawn from 1..max_put_len
    # client op/retry cadence
    op_lo_ns: int = 30_000_000
    op_hi_ns: int = 80_000_000
    # server durability cadence (only meaningful in bug mode — correct
    # mode makes every commit durable synchronously, the S3 contract)
    flush_interval_ns: int = 200_000_000
    # legacy server-crash shorthand, compiled through engine/faults.py;
    # `faults` (below) overrides all four when set
    crashes: int = 1
    crash_window_ns: int = 3_000_000_000
    restart_lo_ns: int = 100_000_000
    restart_hi_ns: int = 800_000_000
    # network model (reference defaults: 1-10 ms latency)
    loss_q32: int = prob_to_q32(0.01)
    lat_lo_ns: int = 1_000_000
    lat_hi_ns: int = 10_000_000
    buggify_q32: int = 0
    # deliberate bug for checker validation: ack at processing time but
    # defer durability to the periodic flush — crash in between loses
    # acknowledged objects
    bug_ack_before_durable: bool = False
    # full declarative fault campaign (engine/faults.FaultSpec); None =
    # derive a server-crash spec from the legacy fields above
    faults: Optional[
        Union[efaults.FaultSpec, efaults.FixedFaults, efaults.FaultEnvelope]
    ] = None

    @property
    def num_nodes(self) -> int:
        return 1 + self.num_clients


def fault_spec(cfg: S3Config) -> efaults.FaultSpec:
    """``cfg.faults`` verbatim, or the legacy server-crash fields lifted
    into a FaultSpec targeting the server node only."""
    if cfg.faults is not None:
        return cfg.faults
    return efaults.FaultSpec(
        crashes=cfg.crashes,
        crash_window_ns=cfg.crash_window_ns,
        restart_lo_ns=cfg.restart_lo_ns,
        restart_hi_ns=cfg.restart_hi_ns,
        crash_group=(SERVER, SERVER + 1),
    )


def _rt(cfg: S3Config, w: "S3State"):
    """Runtime spec view for the in-loop interpreter: the static spec on
    the legacy path, this lane's traced ``FaultRt`` on the envelope path."""
    return efaults.runtime_spec(fault_spec(cfg), w.frt)


class S3State(NamedTuple):
    # shared liveness/pause/partition/burst state (server is node 0)
    fstate: efaults.FaultState
    sgen: jnp.ndarray  # int32 flush-timer generation
    # committed object table [K] (version 0 = never written, len -1 = absent)
    ver_com: jnp.ndarray  # int32[K]
    len_com: jnp.ndarray  # int32[K]
    # durable tier [K] (== committed in correct mode)
    ver_dur: jnp.ndarray  # int32[K]
    len_dur: jnp.ndarray  # int32[K]
    # checker bookkeeping [K]
    last_acked_ver: jnp.ndarray  # int32 highest version a success ack promised
    max_served_ver: jnp.ndarray  # int32 highest version any GET served
    # multipart staging, one active upload per client [NC]
    gen_ctr: jnp.ndarray  # int32 upload-id source
    mp_gen: jnp.ndarray  # int32 registered upload gen (0 = none)
    mp_key: jnp.ndarray  # int32
    mp_mask: jnp.ndarray  # int32 bitmask of staged parts
    mp_done_gen: jnp.ndarray  # int32 last completed gen (idempotent re-ack)
    # clients [NC]
    phase: jnp.ndarray  # int32
    cur_key: jnp.ndarray  # int32
    cur_len: jnp.ndarray  # int32
    cur_gen: jnp.ndarray  # int32
    cur_part: jnp.ndarray  # int32 next part index to upload
    ops_done: jnp.ndarray  # int32
    # network
    links: enet.LinkState
    # sweep outputs
    violation: jnp.ndarray  # bool (any checker)
    vio_ack_loss: jnp.ndarray  # bool
    vio_regress: jnp.ndarray  # bool
    puts: jnp.ndarray  # int32 put_object commits
    gets: jnp.ndarray  # int32 get_object serves
    dels: jnp.ndarray  # int32 delete_object commits
    creates: jnp.ndarray  # int32 multipart registrations
    parts_recv: jnp.ndarray  # int32 distinct parts staged
    completes: jnp.ndarray  # int32 multipart assemblies
    upload_restarts: jnp.ndarray  # int32 NoSuchUpload-driven restarts
    crash_count: jnp.ndarray  # int32 crashes that hit a live server
    msgs_sent: jnp.ndarray  # int32
    msgs_delivered: jnp.ndarray  # int32
    # spec-as-data (engine/faults.py): this lane's runtime override
    # scalars (FaultRt) on the envelope path; a leafless () on the legacy
    # path
    frt: object


def _pay(*vals) -> jnp.ndarray:
    return _common.pay(*vals, slots=PAYLOAD_SLOTS)


_DISABLED = _common.DISABLED


def _emits(*extras) -> Emits:
    """Every handler emits exactly 2 fixed slots (no broadcasts here)."""
    return _common.pack_extras(PAYLOAD_SLOTS, *extras)


# op mix table: 8 slots → phase started (3/8 put, 2/8 get, 1/8 del, 2/8 mp)
_OP_PHASE = (P_PUT, P_PUT, P_PUT, P_GET, P_GET, P_DEL, P_MPC, P_MPC)
# phase → request mtype (IDLE row unused)
_REQ_MTYPE = (0, MT_PUT, MT_GET, MT_DEL, MT_CREATE, MT_PART, MT_COMPLETE)


# -- event handlers (each: (w, now, pay, rand) -> (w, Emits)) ----------------


def _on_op_timer(cfg: S3Config, w: S3State, now, pay, rand):
    """Client c starts a new op (when idle, budget permitting) or re-sends
    the in-flight request, then re-arms (retry-until-ack)."""
    c = pay[0]
    phase = get1(w.phase, c)
    budget_left = get1(w.ops_done, c) < cfg.ops_per_client
    node_up = get1(efaults.up(w.fstate), jnp.asarray(c, jnp.int32) + 1)
    start = (phase == IDLE) & budget_left & node_up

    op = bounded(rand[3], 0, 8)
    op_phase = jnp.take(jnp.array(_OP_PHASE, jnp.int32), op)
    key = bounded(rand[4], 0, cfg.num_keys)
    plen = bounded(rand[5], 1, cfg.max_put_len + 1)

    phase2 = jnp.where(start, op_phase, phase)
    key2 = jnp.where(start, jnp.asarray(key, jnp.int32), get1(w.cur_key, c))
    len2 = jnp.where(start, jnp.asarray(plen, jnp.int32), get1(w.cur_len, c))
    gen = get1(w.cur_gen, c)
    part = get1(w.cur_part, c)

    mtype = jnp.take(jnp.array(_REQ_MTYPE, jnp.int32), phase2)
    a = jnp.where(phase2 >= P_MPP, gen, key2)
    b = jnp.where(
        phase2 == P_PUT, len2, jnp.where(phase2 == P_MPP, part, 0)
    )

    active = (phase2 != IDLE) & node_up
    node = jnp.asarray(c, jnp.int32) + 1
    t, deliver = enet.route(w.links, now, node, SERVER, rand[0], rand[1])
    send = active & deliver
    interval = efaults.skewed_delay(
        fault_spec(cfg), w.fstate, node,
        bounded(rand[2], cfg.op_lo_ns, cfg.op_hi_ns),
        rt=_rt(cfg, w),
    )
    emits = _emits(
        (t, K_MSG, _pay(SERVER, mtype, node, a, b), send),
        (now + interval, K_OP, _pay(c), (phase2 != IDLE) | budget_left),
    )
    w2 = w._replace(
        phase=set1(w.phase, c, phase2, start),
        cur_key=set1(w.cur_key, c, key2, start),
        cur_len=set1(w.cur_len, c, len2, start),
        msgs_sent=w.msgs_sent + jnp.where(active, 1, 0),
        msgs_delivered=w.msgs_delivered + jnp.where(send, 1, 0),
    )
    return w2, emits


def _on_msg(cfg: S3Config, w: S3State, now, pay, rand):
    dst, mtype, src, a, b = pay[0], pay[1], pay[2], pay[3], pay[4]
    at_server = dst == SERVER
    alive = get1(efaults.up(w.fstate), SERVER)
    srv = at_server & alive
    cc = jnp.clip(src - 1, 0, cfg.num_clients - 1)  # requesting client
    sync = not cfg.bug_ack_before_durable  # static: commit == durable

    # -- server: PUT / DELETE — a version bump on the committed tier; a
    # delete is a write of "absent" so per-key versions stay monotone
    # (service.rs:435-479 put/delete both mutate the object entry)
    is_put = srv & (mtype == MT_PUT)
    is_del = srv & (mtype == MT_DEL)
    is_write = is_put | is_del
    wkey = a
    wlen = jnp.where(is_put, b, jnp.int32(-1))
    wver = get1(w.ver_com, wkey) + 1

    # -- server: COMPLETE — assemble staged parts into the object iff the
    # registration is current and every part arrived (service.rs:302-346);
    # a stale gen re-acks if it was the last completed one (idempotency),
    # else NoSuchUpload (service.rs:616-619)
    is_comp = srv & (mtype == MT_COMPLETE)
    comp_cur = (get1(w.mp_gen, cc) == a) & (a != 0)
    full = get1(w.mp_mask, cc) == (1 << cfg.parts_per_upload) - 1
    do_assemble = is_comp & comp_cur & full
    akey = get1(w.mp_key, cc)
    aver = get1(w.ver_com, akey) + 1
    alen = jnp.int32(cfg.parts_per_upload * cfg.part_len)
    comp_reack = is_comp & ~comp_cur & (get1(w.mp_done_gen, cc) == a)

    # apply write then assembly (mutually exclusive — different mtypes)
    ver_com2 = set1(w.ver_com, wkey, wver, is_write)
    len_com2 = set1(w.len_com, wkey, wlen, is_write)
    ver_com2 = set1(ver_com2, akey, aver, do_assemble)
    len_com2 = set1(len_com2, akey, alen, do_assemble)
    if sync:
        ver_dur2 = set1(w.ver_dur, wkey, wver, is_write)
        len_dur2 = set1(w.len_dur, wkey, wlen, is_write)
        ver_dur2 = set1(ver_dur2, akey, aver, do_assemble)
        len_dur2 = set1(len_dur2, akey, alen, do_assemble)
    else:
        ver_dur2, len_dur2 = w.ver_dur, w.len_dur
    # durability promise made the moment the success response is generated
    last_acked2 = set1(w.last_acked_ver, wkey, wver, is_write)
    last_acked2 = set1(last_acked2, akey, aver, do_assemble)

    mp_gen2 = set1(w.mp_gen, cc, jnp.int32(0), do_assemble)
    mp_done_gen2 = set1(w.mp_done_gen, cc, a, do_assemble)

    # -- server: GET — serve the committed version; the monotonic-serve
    # checker latches if a previously served version regressed
    is_get = srv & (mtype == MT_GET)
    gver = get1(ver_com2, a)
    glen = get1(len_com2, a)
    regress = is_get & (gver < get1(w.max_served_ver, a))
    max_served2 = set1(
        w.max_served_ver, a, jnp.maximum(gver, get1(w.max_served_ver, a)), is_get
    )

    # -- server: CREATE — register (or re-ack) this client's upload; a
    # fresh server-issued gen is the upload_id (service.rs:243-267)
    is_create = srv & (mtype == MT_CREATE)
    has_reg = get1(w.mp_gen, cc) != 0
    new_gen = w.gen_ctr + 1
    do_register = is_create & ~has_reg
    gen_ctr2 = jnp.where(do_register, new_gen, w.gen_ctr)
    ack_gen = jnp.where(has_reg, get1(w.mp_gen, cc), new_gen)
    mp_gen2 = set1(mp_gen2, cc, new_gen, do_register)
    mp_key2 = set1(w.mp_key, cc, a, do_register)
    mp_mask2 = set1(w.mp_mask, cc, jnp.int32(0), do_register)

    # -- server: PART — stage into the bitmask iff the gen is current
    # (duplicates from retries are idempotent); stale gen = NoSuchUpload
    is_part = srv & (mtype == MT_PART)
    part_cur = (get1(w.mp_gen, cc) == a) & (a != 0)
    old_mask = get1(mp_mask2, cc)
    bit = jnp.left_shift(jnp.int32(1), b)
    fresh_part = is_part & part_cur & ((old_mask & bit) == 0)
    mp_mask2 = set1(mp_mask2, cc, old_mask | bit, is_part & part_cur)

    # -- server reply (one per request processed while alive)
    rmt = jnp.select(
        [
            is_put,
            is_del,
            is_get,
            is_create,
            is_part & part_cur,
            is_part & ~part_cur,
            do_assemble | comp_reack,
            is_comp & ~(do_assemble | comp_reack),
        ],
        [
            jnp.int32(MT_PUT_ACK),
            jnp.int32(MT_DEL_ACK),
            jnp.int32(MT_GET_RSP),
            jnp.int32(MT_CREATE_ACK),
            jnp.int32(MT_PART_ACK),
            jnp.int32(MT_ERR),
            jnp.int32(MT_COMP_ACK),
            jnp.int32(MT_ERR),
        ],
        jnp.int32(0),
    )
    ra = jnp.select(
        [is_write, is_get, is_create, is_part | is_comp],
        [wver, gver, ack_gen, a],
        jnp.int32(0),
    )
    rb = jnp.select([is_get, is_part], [glen, b], jnp.int32(0))
    # slot 5 echoes the key on put/get/del acks so a delayed ack from an
    # earlier op can't complete a later op on a different key
    rkey = jnp.select([is_write, is_get], [wkey, a], jnp.int32(0))
    did_req = is_write | is_get | is_create | is_part | is_comp
    rt, rdeliver = enet.route(w.links, now, SERVER, src, rand[0], rand[1])
    reply_on = did_req & rdeliver

    # -- client: response handling (stale responses gated by phase/gen)
    at_client = (
        (dst >= 1)
        & (mtype >= MT_PUT_ACK)
        & get1(efaults.up(w.fstate), dst)
    )
    rc = jnp.clip(dst - 1, 0, cfg.num_clients - 1)
    cphase = get1(w.phase, rc)
    cgen = get1(w.cur_gen, rc)
    cpart = get1(w.cur_part, rc)
    key_ok = pay[5] == get1(w.cur_key, rc)

    fin_put = at_client & (mtype == MT_PUT_ACK) & (cphase == P_PUT) & key_ok
    fin_get = at_client & (mtype == MT_GET_RSP) & (cphase == P_GET) & key_ok
    fin_del = at_client & (mtype == MT_DEL_ACK) & (cphase == P_DEL) & key_ok
    got_create = at_client & (mtype == MT_CREATE_ACK) & (cphase == P_MPC)
    got_part = (
        at_client
        & (mtype == MT_PART_ACK)
        & (cphase == P_MPP)
        & (a == cgen)
        & (b == cpart)
    )
    last_part = got_part & (cpart + 1 == cfg.parts_per_upload)
    fin_comp = at_client & (mtype == MT_COMP_ACK) & (cphase == P_MPX) & (a == cgen)
    got_err = (
        at_client
        & (mtype == MT_ERR)
        & ((cphase == P_MPP) | (cphase == P_MPX))
        & (a == cgen)
    )
    fin_op = fin_put | fin_get | fin_del | fin_comp

    nphase = jnp.select(
        [fin_op, got_create, last_part, got_part, got_err],
        [
            jnp.int32(IDLE),
            jnp.int32(P_MPP),
            jnp.int32(P_MPX),
            jnp.int32(P_MPP),
            jnp.int32(P_MPC),  # NoSuchUpload → restart the whole upload
        ],
        cphase,
    )
    touched = fin_op | got_create | got_part | got_err
    phase2 = set1(w.phase, rc, nphase, touched)
    cur_gen2 = set1(w.cur_gen, rc, a, got_create)
    cur_part2 = set1(
        w.cur_part, rc, jnp.where(got_create, jnp.int32(0), cpart + 1),
        got_create | got_part,
    )
    ops_done2 = set1(w.ops_done, rc, get1(w.ops_done, rc) + 1, fin_op)

    emits = _emits(
        (rt, K_MSG, _pay(src, rmt, SERVER, ra, rb, rkey), reply_on),
        _DISABLED,
    )
    w2 = w._replace(
        ver_com=ver_com2,
        len_com=len_com2,
        ver_dur=ver_dur2,
        len_dur=len_dur2,
        last_acked_ver=last_acked2,
        max_served_ver=max_served2,
        gen_ctr=gen_ctr2,
        mp_gen=mp_gen2,
        mp_key=mp_key2,
        mp_mask=mp_mask2,
        mp_done_gen=mp_done_gen2,
        phase=phase2,
        cur_gen=cur_gen2,
        cur_part=cur_part2,
        ops_done=ops_done2,
        vio_regress=w.vio_regress | regress,
        violation=w.violation | regress,
        puts=w.puts + jnp.where(is_put, 1, 0),
        gets=w.gets + jnp.where(is_get, 1, 0),
        dels=w.dels + jnp.where(is_del, 1, 0),
        creates=w.creates + jnp.where(do_register, 1, 0),
        parts_recv=w.parts_recv + jnp.where(fresh_part, 1, 0),
        completes=w.completes + jnp.where(do_assemble, 1, 0),
        upload_restarts=w.upload_restarts + jnp.where(got_err, 1, 0),
        msgs_sent=w.msgs_sent + jnp.where(did_req, 1, 0),
        msgs_delivered=w.msgs_delivered + jnp.where(reply_on, 1, 0),
    )
    return w2, emits


def _on_flush(cfg: S3Config, w: S3State, now, pay, rand):
    """Advance the durable tier to the committed tier (bug mode's only
    durability point) and re-arm. The chain is only armed in bug mode —
    correct mode commits durably at processing time, so the flush would
    be a no-op event every interval (statically gated out in _init /
    _on_restart)."""
    gen = pay[0]
    valid = get1(efaults.up(w.fstate), SERVER) & (gen == w.sgen)
    # the flush is the server's fsync: a slow-disk window (engine/faults
    # ``fsync_stall``) freezes the durable tier while the timer ticks on
    do_flush = valid & ~get1(efaults.stalled(w.fstate), SERVER)
    w2 = w._replace(
        ver_dur=jnp.where(do_flush, w.ver_com, w.ver_dur),
        len_dur=jnp.where(do_flush, w.len_com, w.len_dur),
    )
    flush_dt = efaults.skewed_delay(
        fault_spec(cfg), w.fstate, jnp.int32(SERVER), cfg.flush_interval_ns,
        rt=_rt(cfg, w),
    )
    emits = _emits(
        (now + flush_dt, K_FLUSH, _pay(gen), valid),
        _DISABLED,
    )
    return w2, emits


def _on_fault(cfg: S3Config, w: S3State, now, pay, rand):
    """One event of the compiled fault campaign (engine/faults.py). The
    shared interpreter updates liveness/pause masks and the LinkState;
    this handler adds the S3-specific server consequences:

    - crash: committed state rolls back to the durable tier and every
      staged multipart upload is aborted (ref kill semantics
      task/mod.rs:347-364) — THE checker moment: any acked version
      without a durable copy is an acknowledged-durability breach.
    - pause: the flush-timer chain dies (sgen bump), nothing is lost.
    - restart/resume: a fresh flush-timer chain (bug mode only — correct
      mode commits durably at processing time, see _on_flush)."""
    action, victim = pay[0], pay[1]
    base = efaults.NetBase(cfg.lat_lo_ns, cfg.lat_hi_ns, cfg.loss_q32)
    links2, f2, e = efaults.on_event(
        _rt(cfg, w), base, w.links, w.fstate, action, victim
    )
    at_server = victim == SERVER
    crashed = e.crashed & at_server
    stopped = (e.crashed | e.paused) & at_server
    revived = (e.restarted | e.resumed) & at_server

    lost = jnp.any(w.last_acked_ver > w.ver_dur)
    nc = cfg.num_clients
    sgen2 = w.sgen + jnp.where(stopped, 1, 0)
    w2 = w._replace(
        links=links2,
        fstate=f2,
        sgen=sgen2,
        ver_com=jnp.where(crashed, w.ver_dur, w.ver_com),
        len_com=jnp.where(crashed, w.len_dur, w.len_com),
        mp_gen=jnp.where(crashed, jnp.zeros((nc,), jnp.int32), w.mp_gen),
        mp_done_gen=jnp.where(
            crashed, jnp.zeros((nc,), jnp.int32), w.mp_done_gen
        ),
        vio_ack_loss=w.vio_ack_loss | (crashed & lost),
        violation=w.violation | (crashed & lost),
        crash_count=w.crash_count + jnp.where(crashed, 1, 0),
    )
    rearm = revived if cfg.bug_ack_before_durable else jnp.zeros((), bool)
    emits = _emits(
        (now + cfg.flush_interval_ns, K_FLUSH, _pay(sgen2), rearm),
        _DISABLED,
    )
    return w2, emits


def _handle(cfg: S3Config, w: S3State, now, kind, pay, rand):
    branches = [
        partial(_on_op_timer, cfg),
        partial(_on_msg, cfg),
        partial(_on_flush, cfg),
        partial(_on_fault, cfg),
    ]
    return jax.lax.switch(kind, branches, w, now, pay, rand)


def _init(cfg: S3Config, key, params=None):
    nc, k = cfg.num_clients, cfg.num_keys
    ninit = nc + 1
    rand = jax.random.bits(
        jax.random.fold_in(key, 0x7FFF_FFFF), (ninit,), dtype=jnp.uint32
    )
    w = S3State(
        fstate=efaults.init_state(cfg.num_nodes),
        sgen=jnp.zeros((), jnp.int32),
        ver_com=jnp.zeros((k,), jnp.int32),
        len_com=jnp.full((k,), -1, jnp.int32),
        ver_dur=jnp.zeros((k,), jnp.int32),
        len_dur=jnp.full((k,), -1, jnp.int32),
        last_acked_ver=jnp.zeros((k,), jnp.int32),
        max_served_ver=jnp.zeros((k,), jnp.int32),
        gen_ctr=jnp.zeros((), jnp.int32),
        mp_gen=jnp.zeros((nc,), jnp.int32),
        mp_key=jnp.zeros((nc,), jnp.int32),
        mp_mask=jnp.zeros((nc,), jnp.int32),
        mp_done_gen=jnp.zeros((nc,), jnp.int32),
        phase=jnp.zeros((nc,), jnp.int32),
        cur_key=jnp.zeros((nc,), jnp.int32),
        cur_len=jnp.zeros((nc,), jnp.int32),
        cur_gen=jnp.zeros((nc,), jnp.int32),
        cur_part=jnp.zeros((nc,), jnp.int32),
        ops_done=jnp.zeros((nc,), jnp.int32),
        links=enet.make(
            cfg.num_nodes, cfg.loss_q32, cfg.lat_lo_ns, cfg.lat_hi_ns,
            cfg.buggify_q32,
        ),
        violation=jnp.zeros((), bool),
        vio_ack_loss=jnp.zeros((), bool),
        vio_regress=jnp.zeros((), bool),
        puts=jnp.zeros((), jnp.int32),
        gets=jnp.zeros((), jnp.int32),
        dels=jnp.zeros((), jnp.int32),
        creates=jnp.zeros((), jnp.int32),
        parts_recv=jnp.zeros((), jnp.int32),
        completes=jnp.zeros((), jnp.int32),
        upload_restarts=jnp.zeros((), jnp.int32),
        crash_count=jnp.zeros((), jnp.int32),
        msgs_sent=jnp.zeros((), jnp.int32),
        msgs_delivered=jnp.zeros((), jnp.int32),
        frt=efaults.make_rt(fault_spec(cfg), params),
    )
    times = jnp.zeros((ninit,), jnp.int64)
    kinds = jnp.zeros((ninit,), jnp.int32)
    pays = jnp.zeros((ninit, PAYLOAD_SLOTS), jnp.int32)
    enables = jnp.ones((ninit,), bool)
    for c in range(nc):
        times = times.at[c].set(bounded(rand[c], 0, cfg.op_hi_ns))
        kinds = kinds.at[c].set(K_OP)
        pays = pays.at[c].set(_pay(c))
    # first flush tick (bug mode only — see _on_flush)
    i = nc
    times = times.at[i].set(jnp.int64(cfg.flush_interval_ns))
    kinds = kinds.at[i].set(K_FLUSH)
    pays = pays.at[i].set(_pay(0))
    if not cfg.bug_ack_before_durable:
        enables = enables.at[i].set(False)
    # fault campaign: the shared compiler's event stream, spliced in
    fe = efaults.compile_device(
        fault_spec(cfg), cfg.num_nodes, key, K_FAULT, PAYLOAD_SLOTS,
        params=params,
    )
    return w, Emits(
        times=jnp.concatenate([times, fe.times]),
        kinds=jnp.concatenate([kinds, fe.kinds]),
        pays=jnp.concatenate([pays, fe.pays]),
        enables=jnp.concatenate([enables, fe.enables]),
    )


@_common.memoized_workload(S3Config)
def workload(cfg: S3Config = None) -> Workload:
    """Build the engine Workload for an S3 sweep configuration
    (memoized per config — see _common.memoized_workload)."""
    return Workload(
        init=partial(_init, cfg),
        handle=partial(_handle, cfg),
        num_rand=6,
        payload_slots=PAYLOAD_SLOTS,
        max_emits=2,
    )


def engine_config(cfg: S3Config = S3Config(), **overrides) -> EngineConfig:
    """Engine parameters sized for this workload: steady state holds one
    timer chain + ≤1 in-flight request per client, ≤1 reply per request,
    the flush chain, and the fault plan."""
    defaults = dict(
        queue_capacity=max(
            48,
            4 * cfg.num_clients + 8 + efaults.num_events(fault_spec(cfg)),
        ),
        time_limit_ns=5_000_000_000,
        max_steps=200_000,
    )
    defaults.update(overrides)
    return EngineConfig(**defaults)


# one jitted device program for the whole summary (one transfer) — see
# _common.make_sweep_summary
sweep_summary = _common.make_sweep_summary(
    (
        ("violations", lambda f: f.wstate.violation),
        ("ack_loss_seeds", lambda f: f.wstate.vio_ack_loss),
        ("regress_seeds", lambda f: f.wstate.vio_regress),
        ("puts", lambda f: f.wstate.puts),
        ("gets", lambda f: f.wstate.gets),
        ("dels", lambda f: f.wstate.dels),
        ("creates", lambda f: f.wstate.creates),
        ("parts", lambda f: f.wstate.parts_recv),
        ("completes", lambda f: f.wstate.completes),
        ("upload_restarts", lambda f: f.wstate.upload_restarts),
        ("crashes", lambda f: f.wstate.crash_count),
        # ops_done is per-client [S, NC]: fold the client axis here so
        # the field hands make_sweep_summary the per-LANE vector its
        # contract (and the limit mask) requires
        ("ops_done", lambda f: jnp.sum(f.wstate.ops_done, axis=-1)),
        ("msgs_sent", lambda f: f.wstate.msgs_sent),
        ("msgs_delivered", lambda f: f.wstate.msgs_delivered),
    )
)
