"""Device-expressible workload models for the TPU engine.

The reference drives arbitrary async Rust through its simulator; arbitrary
user code cannot run on a TPU, so the device tier ships table-driven actor
models of the canonical DST workloads (SURVEY.md §7 stage 6). The flagship
is MadRaft-style Raft (models/raft.py) — the workload named by the
BASELINE.md benchmark configs.
"""

from . import etcd, kafka, raft, s3  # noqa: F401

__all__ = ["etcd", "kafka", "raft", "s3"]
