"""Kafka broker/producer/consumer as a device workload — BASELINE config #4.

A single-broker Kafka cluster — per-partition append-only logs with
log-end-offset and durable (flushed) watermark bookkeeping, producers with
retry-until-ack delivery, consumers polling up to the high watermark — with
broker crash/restart fault injection and per-message loss/latency, expressed
as pure array handlers so thousands of seeds run in lockstep on TPU. It is
the second device model after Raft (models/raft.py) and proves the engine
generalizes beyond consensus: same queue/RNG/net substrate, a completely
different actor topology.

Behavior modeled from the reference broker state machine
(madsim-rdkafka/src/sim/broker.rs:80-146 — produce appends at
log_end_offset, fetch reads a bounded batch from an offset, watermarks =
(base, log_end)) plus the crash/restart semantics the reference applies to
any node (madsim/src/sim/task/mod.rs:347-394): on crash the broker loses
every entry newer than its durable watermark, on restart it resumes from
durable state.

Online invariant checkers (any breach latches ``violation``):
- **no acked-message loss**: at crash time, every sequence number the
  broker has acknowledged must have a durable copy (``ack_upto <=
  dur_upto`` per producer). The static ``bug_ack_on_append`` flag makes the
  broker ack on append instead of at flush — the classic
  ack-before-durable bug — which this checker catches at a reported seed.
- **watermark sanity**: the durable watermark never exceeds the log end
  (``flushed <= log_len``), checked at every flush and crash.
- **fetch contiguity / offset monotonicity**: consumers only advance their
  offset on a response matching their current position, so the consumed
  stream is gap-free; the broker never serves past the durable watermark.

Design notes (shared with models/raft.py):
- All node/log indexing is one-hot masked (engine/ops.py) — no dynamic
  scatter/gather on the hot path.
- Timer staleness uses generation counters (``bgen`` guards the broker's
  flush-timer chain across crash/restart); producer/consumer timer chains
  are self-re-arming.
- Acks are *cumulative* (ack_upto = highest acked seq): producers send
  seq k until acked, so per-producer append order has no gaps and a single
  int32 per producer replaces a set.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

from ..engine import faults as efaults
from ..engine import net as enet
from ..engine.core import Emits, EngineConfig, Workload
from ..engine.ops import get1, set1, set2
from ..engine.rng import bounded, prob_to_q32
from ..oracle.history import OP_FETCH, OP_PRODUCE, PH_INVOKE, PH_OK
from . import _common

# event kinds
K_PRODUCE = 0  # pay = (producer,) — producer timer: send next unacked seq
K_FETCH = 1  # pay = (consumer,) — consumer timer: poll from current offset
K_MSG = 2  # pay = (dst_node, mtype, src_node, a, b, c)
K_FLUSH = 3  # pay = (bgen,) — broker durability timer
K_FAULT = 4  # pay = (action, victim, t_lo, t_hi) — engine/faults.py stream

# message types (pay slots a/b/c per type; slot 5 carries the history
# opid on fetch traffic — see _record)
MT_PRODUCE = 0  # a = seq
MT_ACK = 1  # a = ack_upto (cumulative)
MT_FETCH = 2  # a = offset
MT_FETCH_RSP = 3  # a = start_offset, b = num_records

PAYLOAD_SLOTS = 6
BROKER = 0  # node id of the broker

# violation flavors (bitmask latched in ``viol_kind``; ``violation`` stays
# the any-flavor bool). The explore subsystem's triage keys on these.
V_ACK_LOSS = 1  # a crash lost messages the broker had acknowledged
V_WATERMARK = 2  # the durable watermark exceeded the log end


class KafkaConfig(NamedTuple):
    """Static sweep parameters (hashable — part of the jit key)."""

    num_producers: int = 2
    num_consumers: int = 2
    partitions: int = 2
    msgs_per_producer: int = 16
    log_cap: int = 64  # per-partition entry capacity (retries duplicate)
    # producer retry cadence: resend the lowest unacked seq until acked
    produce_lo_ns: int = 30_000_000
    produce_hi_ns: int = 80_000_000
    # consumer poll cadence
    fetch_lo_ns: int = 40_000_000
    fetch_hi_ns: int = 120_000_000
    fetch_max: int = 4  # records per fetch response
    # broker durability cadence (flush marks the log durable)
    flush_interval_ns: int = 200_000_000
    # legacy broker-crash shorthand, compiled through engine/faults.py;
    # `faults` (below) overrides all four when set
    crashes: int = 1
    crash_window_ns: int = 3_000_000_000
    restart_lo_ns: int = 100_000_000
    restart_hi_ns: int = 800_000_000
    # network model (reference defaults: 1-10 ms latency)
    loss_q32: int = prob_to_q32(0.01)
    lat_lo_ns: int = 1_000_000
    lat_hi_ns: int = 10_000_000
    buggify_q32: int = 0
    # deliberate bug for checker validation: ack on append instead of at
    # flush — crash between append and flush loses acknowledged messages
    bug_ack_on_append: bool = False
    # operation-history buffer rows per seed (madsim_tpu/oracle); 0 =
    # recording off. Records produce sends/acks and fetch polls/matches
    # for the ordered-log spec (oracle/specs.LogSpec).
    hist_slots: int = 0
    # full declarative fault campaign (engine/faults.FaultSpec); None =
    # derive a broker-crash spec from the legacy fields above
    faults: Optional[
        Union[efaults.FaultSpec, efaults.FixedFaults, efaults.FaultEnvelope]
    ] = None

    @property
    def num_nodes(self) -> int:
        return 1 + self.num_producers + self.num_consumers


def fault_spec(cfg: KafkaConfig) -> efaults.FaultSpec:
    """``cfg.faults`` verbatim, or the legacy broker-crash fields lifted
    into a FaultSpec targeting the broker node only."""
    if cfg.faults is not None:
        return cfg.faults
    return efaults.FaultSpec(
        crashes=cfg.crashes,
        crash_window_ns=cfg.crash_window_ns,
        restart_lo_ns=cfg.restart_lo_ns,
        restart_hi_ns=cfg.restart_hi_ns,
        crash_group=(BROKER, BROKER + 1),
    )


def _rt(cfg: KafkaConfig, w: "KafkaState"):
    """Runtime spec view for the in-loop interpreter: the static spec on
    the legacy path, this lane's traced ``FaultRt`` on the envelope path."""
    return efaults.runtime_spec(fault_spec(cfg), w.frt)


class KafkaState(NamedTuple):
    # shared liveness/pause/partition/burst state (broker is node 0)
    fstate: efaults.FaultState
    bgen: jnp.ndarray  # int32 flush-timer generation
    # partition logs [P, L] (entries < log_len valid; < flushed durable)
    log_src: jnp.ndarray  # int32[P, L] producer index
    log_seq: jnp.ndarray  # int32[P, L]
    log_len: jnp.ndarray  # int32[P] log end offset
    flushed: jnp.ndarray  # int32[P] durable watermark
    # cumulative ack bookkeeping [NP] (-1 = none)
    ack_upto: jnp.ndarray  # int32 highest seq the broker acked
    dur_upto: jnp.ndarray  # int32 highest seq with a durable copy
    # producers [NP]
    next_seq: jnp.ndarray  # int32 lowest unacked seq (== M when done)
    prod_sends: jnp.ndarray  # int32 produce messages actually on the wire
    # consumers [NC]
    cons_off: jnp.ndarray  # int32 next offset to fetch
    cons_opid: jnp.ndarray  # int32 history opid allocator (fetch ops)
    # network
    links: enet.LinkState
    # sweep outputs
    violation: jnp.ndarray  # bool (any checker)
    viol_kind: jnp.ndarray  # int32 flavor bitmask (V_ACK_LOSS | V_WATERMARK)
    vio_ack_loss: jnp.ndarray  # bool
    vio_watermark: jnp.ndarray  # bool
    log_overflow: jnp.ndarray  # bool
    produced: jnp.ndarray  # int32 produce messages sent
    appended: jnp.ndarray  # int32 entries appended at broker
    acked: jnp.ndarray  # int32 ack messages received by producers
    fetched: jnp.ndarray  # int32 records consumed
    flushes: jnp.ndarray  # int32
    crash_count: jnp.ndarray  # int32 crashes that hit a live broker
    msgs_sent: jnp.ndarray  # int32
    msgs_delivered: jnp.ndarray  # int32
    # spec-as-data (engine/faults.py): this lane's runtime override
    # scalars (FaultRt) on the envelope path; a leafless () on the legacy
    # path
    frt: object


def _pay(*vals) -> jnp.ndarray:
    return _common.pay(*vals, slots=PAYLOAD_SLOTS)


_DISABLED = _common.DISABLED


def _emits(cfg: KafkaConfig, bcast, *extras) -> Emits:
    return _common.pack_emits(PAYLOAD_SLOTS, bcast, *extras)


def _no_bcast(cfg: KafkaConfig):
    return _common.no_bcast(cfg.num_nodes, PAYLOAD_SLOTS, K_MSG)


def _producer_node(p):
    return jnp.asarray(p, jnp.int32) + 1


def _consumer_node(cfg: KafkaConfig, c):
    return jnp.asarray(c, jnp.int32) + 1 + cfg.num_producers


# -- event handlers (each: (w, now, pay, rand) -> (w, Emits)) ----------------


def _on_produce_timer(cfg: KafkaConfig, w: KafkaState, now, pay, rand):
    """Producer p sends its lowest unacked seq to the broker and re-arms
    (retry-until-ack — at-least-once delivery, duplicates possible). A
    crashed/paused producer's timer keeps ticking but sends nothing."""
    p = pay[0]
    seq = get1(w.next_seq, p)
    node = _producer_node(p)
    has_work = seq < cfg.msgs_per_producer
    active = has_work & get1(efaults.up(w.fstate), node)
    t, deliver = enet.route(w.links, now, node, BROKER, rand[0], rand[1])
    send = active & deliver
    msg = _pay(BROKER, MT_PRODUCE, node, seq)
    interval = efaults.skewed_delay(
        fault_spec(cfg), w.fstate, node,
        bounded(rand[2], cfg.produce_lo_ns, cfg.produce_hi_ns),
        rt=_rt(cfg, w),
    )
    emits = _emits(
        cfg,
        _no_bcast(cfg),
        (t, K_MSG, msg, send),
        (now + interval, K_PRODUCE, _pay(p), has_work),
    )
    w2 = w._replace(
        produced=w.produced + jnp.where(active, 1, 0),
        # on-the-wire counter: the record hook's invoke marker (a send
        # that the network dropped never reached the broker, so it is
        # not an op the history needs to explain)
        prod_sends=set1(w.prod_sends, p, get1(w.prod_sends, p) + 1, send),
        msgs_sent=w.msgs_sent + jnp.where(active, 1, 0),
        msgs_delivered=w.msgs_delivered + jnp.where(send, 1, 0),
    )
    return w2, emits


def _on_fetch_timer(cfg: KafkaConfig, w: KafkaState, now, pay, rand):
    """Consumer c polls the broker from its current offset and re-arms; a
    crashed/paused consumer's timer keeps ticking but sends nothing."""
    c = pay[0]
    node = _consumer_node(cfg, c)
    can_send = get1(efaults.up(w.fstate), node)
    t, deliver = enet.route(w.links, now, node, BROKER, rand[0], rand[1])
    sent = can_send & deliver
    opid = get1(w.cons_opid, c)
    msg = _pay(BROKER, MT_FETCH, node, get1(w.cons_off, c), 0, opid)
    interval = efaults.skewed_delay(
        fault_spec(cfg), w.fstate, node,
        bounded(rand[2], cfg.fetch_lo_ns, cfg.fetch_hi_ns),
        rt=_rt(cfg, w),
    )
    emits = _emits(
        cfg,
        _no_bcast(cfg),
        (t, K_MSG, msg, sent),
        (now + interval, K_FETCH, _pay(c), True),
    )
    w2 = w._replace(
        cons_opid=set1(w.cons_opid, c, opid + 1, sent),
        msgs_sent=w.msgs_sent + jnp.where(can_send, 1, 0),
        msgs_delivered=w.msgs_delivered + jnp.where(sent, 1, 0),
    )
    return w2, emits


def _compute_dur_upto(cfg: KafkaConfig, log_src, log_seq, flushed):
    """dur_upto[p] = highest seq among durable entries of producer p.

    Dense [NP, P, L] masked max — per-producer append order is gap-free
    (producers retry seq k until acked before sending k+1), so the max is
    the cumulative durable frontier."""
    pos = jnp.arange(cfg.log_cap, dtype=jnp.int32)[None, :]  # [1, L]
    durable = pos < flushed[:, None]  # [P, L]
    producers = jnp.arange(cfg.num_producers, dtype=jnp.int32)[:, None, None]
    mine = (log_src[None, :, :] == producers) & durable[None, :, :]  # [NP,P,L]
    return jnp.max(
        jnp.where(mine, log_seq[None, :, :], jnp.int32(-1)), axis=(1, 2)
    )


def _on_msg(cfg: KafkaConfig, w: KafkaState, now, pay, rand):
    dst, mtype, src, a, b = pay[0], pay[1], pay[2], pay[3], pay[4]
    at_broker = dst == BROKER
    alive = get1(efaults.up(w.fstate), BROKER)

    # -- broker: PRODUCE — append at log end (broker.rs:80-101); keyed
    # assignment producer → partition (src is the producer's node id)
    is_produce = at_broker & alive & (mtype == MT_PRODUCE)
    producer = src - 1
    part_p = producer % cfg.partitions
    len_p = get1(w.log_len, part_p)
    room = len_p < cfg.log_cap
    do_append = is_produce & room
    seq = a
    log_src2 = set2(w.log_src, part_p, len_p, producer, do_append)
    log_seq2 = set2(w.log_seq, part_p, len_p, seq, do_append)
    log_len2 = set1(w.log_len, part_p, len_p + 1, do_append)

    # ack policy: the deliberate bug acks on append (before the entry is
    # durable); correct behavior acks at flush (_on_flush). Either way a
    # *duplicate* produce of an already-acked seq re-sends the cumulative
    # ack — the original may have been lost in the network, and without a
    # re-send the producer would retry (and duplicate-append) forever.
    if cfg.bug_ack_on_append:
        new_ack_p = jnp.maximum(get1(w.ack_upto, producer), seq)
        ack_upto2 = set1(w.ack_upto, producer, new_ack_p, do_append)
        send_ack = do_append
    else:
        ack_upto2 = w.ack_upto
        new_ack_p = get1(w.ack_upto, producer)
        send_ack = is_produce & (seq <= new_ack_p)

    # -- broker: FETCH — serve up to fetch_max records from the requested
    # offset, bounded by the durable watermark (broker.rs:104-146 bounded
    # fetch; watermark bound = acks-visible semantics)
    is_fetch = at_broker & alive & (mtype == MT_FETCH)
    consumer = src - 1 - cfg.num_producers
    part_c = consumer % cfg.partitions
    off = a
    avail = get1(w.flushed, part_c)
    nrec = jnp.clip(avail - off, 0, cfg.fetch_max)

    # -- producer: ACK (cumulative) — advance next_seq past the frontier;
    # a crashed/paused client drops in-flight receives, like the host
    # tier's kill (tasks die, nothing processes the delivery)
    up = efaults.up(w.fstate)
    is_ack = (
        (mtype == MT_ACK)
        & (dst >= 1)
        & (dst <= cfg.num_producers)
        & get1(up, dst)
    )
    ack_dst = dst - 1
    adv = jnp.maximum(get1(w.next_seq, ack_dst), a + 1)
    next_seq2 = set1(w.next_seq, ack_dst, adv, is_ack)

    # -- consumer: FETCH_RSP — advance only on a response matching the
    # current offset (stale responses from earlier polls are dropped),
    # keeping the consumed stream contiguous and monotonic
    is_rsp = (mtype == MT_FETCH_RSP) & (dst > cfg.num_producers) & get1(up, dst)
    rsp_c = dst - 1 - cfg.num_producers
    match = is_rsp & (a == get1(w.cons_off, rsp_c))
    cons_off2 = set1(w.cons_off, rsp_c, a + b, match)

    # reply slot: ACK (produce, bug mode) or FETCH_RSP (fetch)
    rt, rdeliver = enet.route(w.links, now, BROKER, src, rand[0], rand[1])
    reply_pay = jnp.where(
        is_fetch,
        # slot 5 echoes the fetch's history opid back to the consumer
        _pay(src, MT_FETCH_RSP, BROKER, off, nrec, pay[5]),
        _pay(src, MT_ACK, BROKER, new_ack_p),
    )
    reply_on = (is_fetch | send_ack) & rdeliver
    reply_sent = is_fetch | send_ack

    emits = _emits(
        cfg,
        _no_bcast(cfg),
        (rt, K_MSG, reply_pay, reply_on),
        _DISABLED,
    )
    w2 = w._replace(
        log_src=log_src2,
        log_seq=log_seq2,
        log_len=log_len2,
        ack_upto=ack_upto2,
        next_seq=next_seq2,
        cons_off=cons_off2,
        log_overflow=w.log_overflow | (is_produce & ~room),
        appended=w.appended + jnp.where(do_append, 1, 0),
        acked=w.acked + jnp.where(is_ack, 1, 0),
        fetched=w.fetched + jnp.where(match, b, 0),
        msgs_sent=w.msgs_sent + jnp.where(reply_sent, 1, 0),
        msgs_delivered=w.msgs_delivered + jnp.where(reply_on, 1, 0),
    )
    return w2, emits


def _on_flush(cfg: KafkaConfig, w: KafkaState, now, pay, rand):
    """Advance the durable watermark to the log end; in correct mode this
    is also the ack point — one cumulative ack per producer whose durable
    frontier moved.

    The broker's flush IS its fsync: inside a slow-disk window
    (engine/faults ``fsync_stall``) the timer keeps ticking but the
    watermark freezes — nothing becomes durable until the window closes,
    so a crash/power_fail meanwhile loses every entry past the stalled
    frontier (and, in bug_ack_on_append mode, acknowledged data)."""
    gen = pay[0]
    valid = get1(efaults.up(w.fstate), BROKER) & (gen == w.bgen)
    do_flush = valid & ~get1(efaults.stalled(w.fstate), BROKER)
    flushed2 = jnp.where(do_flush, w.log_len, w.flushed)
    dur2 = jnp.where(
        do_flush,
        _compute_dur_upto(cfg, w.log_src, w.log_seq, flushed2),
        w.dur_upto,
    )
    # watermark sanity: the durable watermark must not already exceed the
    # log end when the flush fires (checked pre-update; post-update the
    # two are equal by construction)
    bad_wm = do_flush & jnp.any(w.flushed > w.log_len)

    if cfg.bug_ack_on_append:
        ack2 = w.ack_upto  # acks already went out at append time
        advanced = jnp.zeros((cfg.num_producers,), bool)
    else:
        advanced = do_flush & (dur2 > w.ack_upto)
        ack2 = jnp.where(advanced, dur2, w.ack_upto)

    # broadcast slots: one cumulative ack per producer with a moved
    # frontier (slots for non-producer nodes stay disabled)
    n = cfg.num_nodes
    u = rand[: 2 * n].reshape(n, 2)
    times, deliver = enet.route_from(w.links, now, BROKER, u[:, 0], u[:, 1])
    node_ids = jnp.arange(n, dtype=jnp.int32)
    is_producer_slot = (node_ids >= 1) & (node_ids <= cfg.num_producers)
    slot_producer = jnp.clip(node_ids - 1, 0, cfg.num_producers - 1)
    slot_adv = jnp.take(advanced, slot_producer) & is_producer_slot
    slot_ack = jnp.take(ack2, slot_producer)
    pays = jnp.stack(
        [
            node_ids,
            jnp.full((n,), MT_ACK, jnp.int32),
            jnp.full((n,), BROKER, jnp.int32),
            slot_ack,
            jnp.zeros((n,), jnp.int32),
            jnp.zeros((n,), jnp.int32),
        ],
        axis=1,
    )
    enables = slot_adv & deliver
    bcast = (times, jnp.full((n,), K_MSG, jnp.int32), pays, enables)

    flush_dt = efaults.skewed_delay(
        fault_spec(cfg), w.fstate, jnp.int32(BROKER), cfg.flush_interval_ns,
        rt=_rt(cfg, w),
    )
    emits = _emits(
        cfg,
        bcast,
        (now + flush_dt, K_FLUSH, _pay(gen), valid),
        _DISABLED,
    )
    w2 = w._replace(
        flushed=flushed2,
        dur_upto=dur2,
        ack_upto=ack2,
        flushes=w.flushes + jnp.where(do_flush, 1, 0),
        vio_watermark=w.vio_watermark | bad_wm,
        violation=w.violation | bad_wm,
        viol_kind=w.viol_kind
        | jnp.where(bad_wm, jnp.int32(V_WATERMARK), jnp.int32(0)),
        msgs_sent=w.msgs_sent + jnp.sum(slot_adv, dtype=jnp.int32),
        msgs_delivered=w.msgs_delivered + jnp.sum(enables, dtype=jnp.int32),
    )
    return w2, emits


def _on_fault(cfg: KafkaConfig, w: KafkaState, now, pay, rand):
    """One event of the compiled fault campaign (engine/faults.py). The
    shared interpreter updates liveness/pause masks and the LinkState;
    this handler adds the Kafka-specific consequences for the broker:

    - crash: everything newer than the durable watermark is lost (ref
      kill semantics task/mod.rs:347-364) — THE checker moment: any
      acked-but-not-durable seq is acknowledged data loss.
    - pause: the flush-timer chain dies (bgen bump) but no data is lost.
    - restart/resume: a fresh flush-timer chain from durable state.

    Client-node faults need no handler work: producer/consumer timers
    gate their sends — and _on_msg their receives — on the shared
    liveness mask directly."""
    action, victim = pay[0], pay[1]
    base = efaults.NetBase(cfg.lat_lo_ns, cfg.lat_hi_ns, cfg.loss_q32)
    links2, f2, e = efaults.on_event(
        _rt(cfg, w), base, w.links, w.fstate, action, victim
    )
    at_broker = victim == BROKER
    crashed = e.crashed & at_broker
    stopped = (e.crashed | e.paused) & at_broker  # flush chain must die
    revived = (e.restarted | e.resumed) & at_broker  # ... and be re-armed

    lost_acked = jnp.any(w.ack_upto > w.dur_upto)
    bad_wm = jnp.any(w.flushed > w.log_len)
    bgen2 = w.bgen + jnp.where(stopped, 1, 0)
    w2 = w._replace(
        links=links2,
        fstate=f2,
        bgen=bgen2,
        log_len=jnp.where(crashed, w.flushed, w.log_len),
        vio_ack_loss=w.vio_ack_loss | (crashed & lost_acked),
        vio_watermark=w.vio_watermark | (crashed & bad_wm),
        violation=w.violation | (crashed & (lost_acked | bad_wm)),
        viol_kind=w.viol_kind
        | jnp.where(crashed & lost_acked, jnp.int32(V_ACK_LOSS), jnp.int32(0))
        | jnp.where(crashed & bad_wm, jnp.int32(V_WATERMARK), jnp.int32(0)),
        crash_count=w.crash_count + jnp.where(crashed, 1, 0),
    )
    flush_dt = efaults.skewed_delay(
        fault_spec(cfg), f2, jnp.int32(BROKER), cfg.flush_interval_ns,
        rt=_rt(cfg, w),
    )
    emits = _emits(
        cfg,
        _no_bcast(cfg),
        (now + flush_dt, K_FLUSH, _pay(bgen2), revived),
        _DISABLED,
    )
    return w2, emits


def _handle(cfg: KafkaConfig, w: KafkaState, now, kind, pay, rand):
    branches = [
        partial(_on_produce_timer, cfg),
        partial(_on_fetch_timer, cfg),
        partial(_on_msg, cfg),
        partial(_on_flush, cfg),
        partial(_on_fault, cfg),
    ]
    return jax.lax.switch(kind, branches, w, now, pay, rand)


def _probe(w: KafkaState):
    """Violation-flavor bitmask (engine contract: ``Workload.probe``) —
    recorded per step by ``run_traced`` so triage can locate the first
    violating event."""
    return w.viol_kind


def _record(cfg: KafkaConfig, wb: KafkaState, wa: KafkaState, now, kind, pay):
    """Map one dispatched event to its op-history record (engine
    contract: ``Workload.record`` — at most ONE row per event).

    History clients: producers are 0..NP-1, consumers NP..NP+NC-1. A
    produce send's opid is its seq (retries re-invoke the same id; the
    decoder keeps the superseded invoke open, which the checker treats
    as optional — sound). A cumulative ack that advances the producer's
    frontier completes the frontier seq; skipped seqs stay open. Fetches
    use a per-consumer opid echoed through pay slot 5, completed only on
    the offset-matching response — so recorded completions are exactly
    the committed ones, which is what LogSpec's contiguity pre-check
    keys on."""
    np_ = cfg.num_producers

    # produce invoke: the timer put seq on the wire (prod_sends bumped)
    p = jnp.clip(pay[0], 0, np_ - 1)
    p_sent = (kind == K_PRODUCE) & (
        get1(wa.prod_sends, p) > get1(wb.prod_sends, p)
    )
    p_seq = get1(wb.next_seq, p)

    # fetch invoke: the poll timer sent (cons_opid bumped)
    c = jnp.clip(pay[0], 0, cfg.num_consumers - 1)
    f_sent = (kind == K_FETCH) & (
        get1(wa.cons_opid, c) > get1(wb.cons_opid, c)
    )
    f_opid = get1(wb.cons_opid, c)
    f_off = get1(wb.cons_off, c)

    # completions ride on delivered K_MSG events at the clients
    dst, mtype, a, b = pay[0], pay[1], pay[3], pay[4]
    ack_p = jnp.clip(dst - 1, 0, np_ - 1)
    acked = (kind == K_MSG) & (mtype == MT_ACK) & (
        get1(wa.next_seq, ack_p) > get1(wb.next_seq, ack_p)
    )
    rsp_c = jnp.clip(dst - 1 - np_, 0, cfg.num_consumers - 1)
    matched = (
        (kind == K_MSG)
        & (mtype == MT_FETCH_RSP)
        & (get1(wa.cons_off, rsp_c) > get1(wb.cons_off, rsp_c))
    )

    def pick(pv, fv, av, mv):
        pv, fv = jnp.asarray(pv, jnp.int32), jnp.asarray(fv, jnp.int32)
        av, mv = jnp.asarray(av, jnp.int32), jnp.asarray(mv, jnp.int32)
        return jnp.where(
            p_sent, pv, jnp.where(f_sent, fv, jnp.where(acked, av, mv))
        )

    rec = jnp.stack(
        [
            pick(p, np_ + c, ack_p, np_ + rsp_c),
            pick(
                OP_PRODUCE * 2 + PH_INVOKE,
                OP_FETCH * 2 + PH_INVOKE,
                OP_PRODUCE * 2 + PH_OK,
                OP_FETCH * 2 + PH_OK,
            ),
            pick(
                p % cfg.partitions,
                c % cfg.partitions,
                ack_p % cfg.partitions,
                rsp_c % cfg.partitions,
            ),
            pick(p_seq, f_off, a, b),
            pick(p_seq, f_opid, a, pay[5]),
        ]
    )
    return rec, p_sent | f_sent | acked | matched


def _init(cfg: KafkaConfig, key, params=None):
    np_, nc = cfg.num_producers, cfg.num_consumers
    ninit = np_ + nc + 1
    rand = jax.random.bits(
        jax.random.fold_in(key, 0x7FFF_FFFF), (ninit,), dtype=jnp.uint32
    )
    w = KafkaState(
        fstate=efaults.init_state(cfg.num_nodes),
        bgen=jnp.zeros((), jnp.int32),
        log_src=jnp.full((cfg.partitions, cfg.log_cap), -1, jnp.int32),
        log_seq=jnp.full((cfg.partitions, cfg.log_cap), -1, jnp.int32),
        log_len=jnp.zeros((cfg.partitions,), jnp.int32),
        flushed=jnp.zeros((cfg.partitions,), jnp.int32),
        ack_upto=jnp.full((np_,), -1, jnp.int32),
        dur_upto=jnp.full((np_,), -1, jnp.int32),
        next_seq=jnp.zeros((np_,), jnp.int32),
        prod_sends=jnp.zeros((np_,), jnp.int32),
        cons_off=jnp.zeros((nc,), jnp.int32),
        cons_opid=jnp.zeros((nc,), jnp.int32),
        links=enet.make(
            cfg.num_nodes, cfg.loss_q32, cfg.lat_lo_ns, cfg.lat_hi_ns,
            cfg.buggify_q32,
        ),
        violation=jnp.zeros((), bool),
        viol_kind=jnp.zeros((), jnp.int32),
        vio_ack_loss=jnp.zeros((), bool),
        vio_watermark=jnp.zeros((), bool),
        log_overflow=jnp.zeros((), bool),
        produced=jnp.zeros((), jnp.int32),
        appended=jnp.zeros((), jnp.int32),
        acked=jnp.zeros((), jnp.int32),
        fetched=jnp.zeros((), jnp.int32),
        flushes=jnp.zeros((), jnp.int32),
        crash_count=jnp.zeros((), jnp.int32),
        msgs_sent=jnp.zeros((), jnp.int32),
        msgs_delivered=jnp.zeros((), jnp.int32),
        frt=efaults.make_rt(fault_spec(cfg), params),
    )
    times = jnp.zeros((ninit,), jnp.int64)
    kinds = jnp.zeros((ninit,), jnp.int32)
    pays = jnp.zeros((ninit, PAYLOAD_SLOTS), jnp.int32)
    enables = jnp.ones((ninit,), bool)
    for p in range(np_):
        times = times.at[p].set(bounded(rand[p], 0, cfg.produce_hi_ns))
        kinds = kinds.at[p].set(K_PRODUCE)
        pays = pays.at[p].set(_pay(p))
    for c in range(nc):
        i = np_ + c
        times = times.at[i].set(bounded(rand[i], 0, cfg.fetch_hi_ns))
        kinds = kinds.at[i].set(K_FETCH)
        pays = pays.at[i].set(_pay(c))
    # first flush tick
    i = np_ + nc
    times = times.at[i].set(jnp.int64(cfg.flush_interval_ns))
    kinds = kinds.at[i].set(K_FLUSH)
    pays = pays.at[i].set(_pay(0))
    # fault campaign: the shared compiler's event stream, spliced in
    fe = efaults.compile_device(
        fault_spec(cfg), cfg.num_nodes, key, K_FAULT, PAYLOAD_SLOTS,
        params=params,
    )
    return w, Emits(
        times=jnp.concatenate([times, fe.times]),
        kinds=jnp.concatenate([kinds, fe.kinds]),
        pays=jnp.concatenate([pays, fe.pays]),
        enables=jnp.concatenate([enables, fe.enables]),
    )


def history_spec():
    """The sequential spec this model's recorded histories check
    against (oracle/specs.LogSpec) — also the key the device screen
    dispatches on (oracle/screen.screen_for), so a checked sweep needs
    no per-call-site spec plumbing."""
    from ..oracle.specs import LogSpec

    return LogSpec()


@_common.memoized_workload(KafkaConfig)
def workload(cfg: KafkaConfig = None) -> Workload:
    """Build the engine Workload for a Kafka sweep configuration
    (memoized per config — see _common.memoized_workload)."""
    return Workload(
        init=partial(_init, cfg),
        handle=partial(_handle, cfg),
        num_rand=2 * cfg.num_nodes + 3,
        payload_slots=PAYLOAD_SLOTS,
        max_emits=cfg.num_nodes + 2,
        probe=_probe,
        record=partial(_record, cfg) if cfg.hist_slots > 0 else None,
        hist_slots=cfg.hist_slots,
    )


def engine_config(cfg: KafkaConfig = KafkaConfig(), **overrides) -> EngineConfig:
    """Engine parameters sized for this workload: steady state holds one
    timer chain per actor, ≤1 in-flight request+reply per client, ≤NP
    flush acks, and the fault plan."""
    defaults = dict(
        queue_capacity=max(
            48,
            4 * (cfg.num_producers + cfg.num_consumers)
            + cfg.num_nodes
            + efaults.num_events(fault_spec(cfg))
            + 4,
        ),
        time_limit_ns=5_000_000_000,
        max_steps=200_000,
    )
    defaults.update(overrides)
    return EngineConfig(**defaults)


# one jitted device program for the whole summary (one transfer) — see
# _common.make_sweep_summary
sweep_summary = _common.make_sweep_summary(
    (
        ("violations", lambda f: f.wstate.violation),
        ("ack_loss_seeds", lambda f: f.wstate.vio_ack_loss),
        ("watermark_seeds", lambda f: f.wstate.vio_watermark),
        ("produced", lambda f: f.wstate.produced),
        ("appended", lambda f: f.wstate.appended),
        ("acked", lambda f: f.wstate.acked),
        ("fetched", lambda f: f.wstate.fetched),
        ("flushes", lambda f: f.wstate.flushes),
        ("crashes", lambda f: f.wstate.crash_count),
        ("log_overflow_seeds", lambda f: f.wstate.log_overflow),
        ("msgs_sent", lambda f: f.wstate.msgs_sent),
        ("msgs_delivered", lambda f: f.wstate.msgs_delivered),
    )
)
