"""Forced-CPU-mesh environment recipe (jax-free, import-safe anywhere).

A JAX process can emulate an n-device mesh on one host by setting
``JAX_PLATFORMS=cpu`` and ``--xla_force_host_platform_device_count=n``
*before* JAX initializes. Two call sites need the exact same recipe —
``tests/conftest.py`` (pytest env) and ``__graft_entry__.dryrun_multichip``
(the driver's multi-chip gate subprocess) — so it lives here once.

TPU-plugin sitecustomizes (gated on ``PALLAS_AXON_POOL_IPS``) re-register
the device backend and override ``jax_platforms`` after init; the gate env
var is dropped so the target interpreter stays CPU-only.
"""

from __future__ import annotations

import re
import sys
from typing import MutableMapping

_FLAG = "--xla_force_host_platform_device_count"
_FLAG_RE = re.compile(re.escape(_FLAG) + r"=(\d+)")


def force_cpu_mesh_env(env: MutableMapping[str, str], n_devices: int) -> None:
    """Mutate ``env`` so a fresh interpreter sees >= n_devices CPU devices.

    An existing device-count flag is raised to ``n_devices`` (never
    lowered — a larger pre-set mesh still satisfies the caller).
    """
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = env.get("XLA_FLAGS", "")
    m = _FLAG_RE.search(flags)
    if m:
        count = max(int(m.group(1)), n_devices)
        flags = _FLAG_RE.sub(f"{_FLAG}={count}", flags)
    else:
        flags = (flags + f" {_FLAG}={n_devices}").strip()
    env["XLA_FLAGS"] = flags


def reexec_with_cpu_mesh(n_devices: int) -> None:
    """Re-exec ``sys.argv`` under the forced CPU mesh when this process
    sees fewer than ``n_devices`` devices (or its backend fails to
    init); no-op when enough devices already exist.

    The multi-device demo scripts (scripts/multichip_campaign.py,
    checked_sweep_demo --mesh, sweep_million --mesh) call this first
    thing in ``main``: env vars alone are too late once jax has picked
    a backend, so the script restarts itself in a child with the env
    fixed and exits with the child's code. The marker env var stops a
    child that STILL lacks devices from recursing."""
    import os
    import subprocess
    import sys

    if os.environ.get("_MADSIM_MESH_REEXEC") == "1":
        import jax

        have = len(jax.devices())
        if have < n_devices:
            # don't return silently: callers would shard over fewer
            # devices than they report (and recursing can't help)
            raise RuntimeError(
                f"re-exec'd under the forced CPU mesh but still see "
                f"{have} < {n_devices} devices — is something clobbering "
                "XLA_FLAGS/JAX_PLATFORMS in this environment?"
            )
        return
    have = 0
    try:
        import jax

        have = len(jax.devices())
    except Exception:
        have = 0  # backend init failed; the CPU-mesh child still works
    if have >= n_devices:
        return
    env = dict(os.environ)
    env["_MADSIM_MESH_REEXEC"] = "1"
    force_cpu_mesh_env(env, n_devices)
    raise SystemExit(
        subprocess.run([sys.executable] + sys.argv, env=env).returncode
    )


def apply_in_process() -> None:
    """Force the cpu platform even if jax was already imported.

    Sitecustomize hooks can import (and platform-pin) jax at interpreter
    startup, before any user code runs; env vars alone are then too late.
    ``jax.config.update`` still wins as long as no backend has been
    initialized, which is the case at conftest-import time.
    """
    if "jax" in sys.modules:
        import jax

        jax.config.update("jax_platforms", "cpu")
