"""Forced-CPU-mesh environment recipe (jax-free, import-safe anywhere).

A JAX process can emulate an n-device mesh on one host by setting
``JAX_PLATFORMS=cpu`` and ``--xla_force_host_platform_device_count=n``
*before* JAX initializes. Two call sites need the exact same recipe —
``tests/conftest.py`` (pytest env) and ``__graft_entry__.dryrun_multichip``
(the driver's multi-chip gate subprocess) — so it lives here once.

TPU-plugin sitecustomizes (gated on ``PALLAS_AXON_POOL_IPS``) re-register
the device backend and override ``jax_platforms`` after init; the gate env
var is dropped so the target interpreter stays CPU-only.
"""

from __future__ import annotations

import re
import sys
from typing import MutableMapping

_FLAG = "--xla_force_host_platform_device_count"
_FLAG_RE = re.compile(re.escape(_FLAG) + r"=(\d+)")


def force_cpu_mesh_env(env: MutableMapping[str, str], n_devices: int) -> None:
    """Mutate ``env`` so a fresh interpreter sees >= n_devices CPU devices.

    An existing device-count flag is raised to ``n_devices`` (never
    lowered — a larger pre-set mesh still satisfies the caller).
    """
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = env.get("XLA_FLAGS", "")
    m = _FLAG_RE.search(flags)
    if m:
        count = max(int(m.group(1)), n_devices)
        flags = _FLAG_RE.sub(f"{_FLAG}={count}", flags)
    else:
        flags = (flags + f" {_FLAG}={n_devices}").strip()
    env["XLA_FLAGS"] = flags


def apply_in_process() -> None:
    """Force the cpu platform even if jax was already imported.

    Sitecustomize hooks can import (and platform-pin) jax at interpreter
    startup, before any user code runs; env vars alone are then too late.
    ``jax.config.update`` still wins as long as no backend has been
    initialized, which is the case at conftest-import time.
    """
    if "jax" in sys.modules:
        import jax

        jax.config.update("jax_platforms", "cpu")
