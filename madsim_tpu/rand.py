"""Deterministic global RNG — the *only* source of randomness in a simulation.

Mirrors the reference's ``GlobalRng`` (madsim/src/sim/rand.rs:28-135): a seeded
counter-based generator behind the runtime handle; every draw in the entire
simulation (scheduler pops, time jitter, network latency/loss, buggify, user
``rand.random()`` calls) flows through it, which is what makes one seed = one
bit-exact execution.

Where the reference uses Xoshiro256++ plus ``#[no_mangle]`` libc interposition
of getrandom/getentropy (rand.rs:197-260), we use numpy's Philox counter-based
bit generator (stable across platforms/versions by numpy's stream-compat
policy) plus Python-level interposition of the stdlib ``random``/``uuid``
modules (see madsim_tpu.interpose).

Determinism log/check (rand.rs:64-88): with logging enabled, every draw
appends ``mix64(value ^ sim_time_ns)`` to a log; a second run with checking
enabled compares draw-by-draw and raises ``NondeterminismError`` with the sim
timestamp at the first divergence.
"""

from __future__ import annotations

from typing import Any, List, MutableSequence, Optional, Sequence, TypeVar

import numpy as np

from .context import _tls as _ctx_tls, current_handle

T = TypeVar("T")

_MASK64 = (1 << 64) - 1


def mix64(x: int) -> int:
    """splitmix64 finalizer — stable 64-bit hash used for the determinism log."""
    x &= _MASK64
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & _MASK64
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & _MASK64
    return x ^ (x >> 31)


class NondeterminismError(RuntimeError):
    """Raised by the determinism checker at the first divergent RNG draw."""

    def __init__(self, sim_time_ns: int, draw_index: int):
        self.sim_time_ns = sim_time_ns
        self.draw_index = draw_index
        super().__init__(
            f"non-determinism detected at simulated time "
            f"{sim_time_ns / 1e9:.9f}s (rng draw #{draw_index}); "
            f"the workload consumed randomness differently between two runs "
            f"of the same seed"
        )


# One numpy refill per this many draws; the refill bound and the refill
# size MUST stay equal or draws would silently repeat or skip the buffer
# tail (the C fast path in simloop.c reads the same buffer via _buf_pos,
# so the coupling crosses the language boundary).
_BUF_SIZE = 1024


class GlobalRng:
    """Seeded deterministic RNG + determinism log/check + buggify gate.

    Reference: ``GlobalRng::{new_with_seed, with, enable_log, enable_check,
    buggify}`` (madsim/src/sim/rand.rs:28-135).
    """

    def __init__(self, seed: int):
        self.seed = int(seed) & _MASK64
        self._gen = np.random.Generator(np.random.Philox(key=self.seed))
        # buffered draws: one numpy call per _BUF_SIZE values — a per-draw
        # Generator.integers() call costs ~8 µs of numpy dispatch and was
        # ~25% of host-tier wall time; the batched stream is identical
        # for a given seed (the determinism contract is per-seed
        # reproducibility, which buffering preserves)
        self._buf = None
        self._buf_pos = 0
        # determinism log/check state
        self._log: Optional[List[int]] = None
        self._check: Optional[List[int]] = None
        self._check_pos = 0
        self._draw_count = 0
        # buggify (sim/buggify.rs; gate lives in rand.rs:113-134 in the ref)
        self.buggify_enabled = False
        self.buggify_prob = 0.25  # default fire rate of bare buggify()
        # set by TimeHandle so log entries carry sim time
        self._now_ns = lambda: 0

    # -- determinism log / check (rand.rs:64-88) --------------------------

    def enable_log(self) -> None:
        self._log = []

    def take_log(self) -> Optional[List[int]]:
        log, self._log = self._log, None
        return log

    def enable_check(self, log: List[int]) -> None:
        self._check = log
        self._check_pos = 0

    def _record(self, value: int) -> None:
        self._draw_count += 1
        if self._log is None and self._check is None:
            return
        digest = mix64(value ^ self._now_ns())
        if self._log is not None:
            self._log.append(digest)
        if self._check is not None:
            pos = self._check_pos
            self._check_pos += 1
            if pos >= len(self._check) or self._check[pos] != digest:
                raise NondeterminismError(self._now_ns(), self._draw_count - 1)

    # -- raw draws --------------------------------------------------------

    def next_u64(self) -> int:
        pos = self._buf_pos
        buf = self._buf
        if buf is None or pos >= _BUF_SIZE:
            # .tolist() once per refill: indexing a Python list yields ints
            # directly, vs a numpy scalar + int() conversion per draw
            buf = self._buf = self._gen.integers(
                0, 1 << 64, size=_BUF_SIZE, dtype=np.uint64
            ).tolist()
            pos = 0
        self._buf_pos = pos + 1
        v = buf[pos]
        if self._log is None and self._check is None:
            self._draw_count += 1  # inlined _record fast path
        else:
            self._record(v)
        return v

    def next_u32(self) -> int:
        return self.next_u64() >> 32

    def random(self) -> float:
        """Uniform float in [0, 1) with 53 bits of entropy."""
        return (self.next_u64() >> 11) * (1.0 / (1 << 53))

    # -- derived draws ----------------------------------------------------

    def gen_range(self, low: int, high: int) -> int:
        """Uniform integer in [low, high) — rejection-free Lemire reduction."""
        if high <= low:
            raise ValueError(f"empty range [{low}, {high})")
        span = high - low
        return low + (self.next_u64() * span >> 64)

    def uniform(self, low: float, high: float) -> float:
        return low + (high - low) * self.random()

    def randbool(self, p: float = 0.5) -> bool:
        return self.random() < p

    def shuffle(self, seq: MutableSequence[Any]) -> None:
        # Fisher-Yates with our draws so it lands in the determinism log.
        for i in range(len(seq) - 1, 0, -1):
            j = self.gen_range(0, i + 1)
            seq[i], seq[j] = seq[j], seq[i]

    def choice(self, seq: Sequence[T]) -> T:
        if not seq:
            raise IndexError("choice from empty sequence")
        return seq[self.gen_range(0, len(seq))]

    def sample_bytes(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            out += self.next_u64().to_bytes(8, "little")
        return bytes(out[:n])

    # -- buggify gate (sim/buggify.rs:8-32, gate in rand.rs:113-134) ------

    def buggify_with_prob(self, prob: float) -> bool:
        if not self.buggify_enabled:
            return False
        return self.random() < prob

    def buggify(self) -> bool:
        return self.buggify_with_prob(self.buggify_prob)


# -- ambient-context convenience API (rand.rs thread_rng/random) ----------


def rng() -> GlobalRng:
    """The current simulation's RNG (reference ``thread_rng``)."""
    # hand-inlined ambient lookup (hot: every module-level draw)
    h = getattr(_ctx_tls, "handle", None)
    if h is None:
        return current_handle().rng  # raises NoContextError
    return h.rng


def random() -> float:
    return rng().random()


def next_u64() -> int:
    return rng().next_u64()


def next_u32() -> int:
    return rng().next_u32()


def gen_range(low: int, high: int) -> int:
    return rng().gen_range(low, high)


def uniform(low: float, high: float) -> float:
    return rng().uniform(low, high)


def shuffle(seq: MutableSequence[Any]) -> None:
    rng().shuffle(seq)


def choice(seq: Sequence[T]) -> T:
    return rng().choice(seq)


def getrandom(n: int) -> bytes:
    """Deterministic entropy — the analogue of the libc ``getrandom``
    interposition (rand.rs:197-241)."""
    return rng().sample_bytes(n)
