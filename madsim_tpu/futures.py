"""Future / waker machinery for the deterministic executor.

The reference builds on Rust's ``async-task`` + ``Waker`` protocol; the Python
equivalent here is a minimal trampoline: coroutines ``yield`` *pollable*
objects to the executor, which calls ``pollable.subscribe(task)`` so the task
is re-enqueued (woken) when the pollable resolves.  Spurious wakes are fine —
``__await__`` loops until done, exactly like a Rust future returning
``Poll::Pending``.

Everything awaitable inside the simulation is either a coroutine or derives
from :class:`Future` (one-shot resolvable cell with a waker list).
"""

from __future__ import annotations

from typing import Any, Generator, Iterable, List, Optional, TYPE_CHECKING

if TYPE_CHECKING:
    from .task import Task

_PENDING = object()


class CancelledError(RuntimeError):
    """The awaited task/future was cancelled (tokio ``JoinError::Cancelled``)."""


class JoinError(RuntimeError):
    """Awaited task failed; ``.cause`` holds the original exception."""

    def __init__(self, cause: BaseException):
        self.cause = cause
        super().__init__(f"task panicked: {cause!r}")


class Future:
    """One-shot resolvable value with deterministic FIFO waker list."""

    __slots__ = ("_value", "_exc", "_wakers")

    def __init__(self) -> None:
        self._value: Any = _PENDING
        self._exc: Optional[BaseException] = None
        self._wakers: List["Task"] = []

    # -- state ------------------------------------------------------------

    def done(self) -> bool:
        return self._value is not _PENDING or self._exc is not None

    def result(self) -> Any:
        if self._exc is not None:
            raise self._exc
        if self._value is _PENDING:
            raise RuntimeError("future is not resolved yet")
        return self._value

    def exception(self) -> Optional[BaseException]:
        return self._exc

    def set_result(self, value: Any) -> None:
        if self.done():
            return
        self._value = value
        self._wake_all()

    def set_exception(self, exc: BaseException) -> None:
        if self.done():
            return
        self._exc = exc
        self._wake_all()

    def _reset(self) -> None:
        """Re-arm a resolved future (used by Sleep.reset)."""
        self._value = _PENDING
        self._exc = None

    def _wake_all(self) -> None:
        wakers, self._wakers = self._wakers, []
        for t in wakers:
            t.wake()

    # -- pollable protocol -------------------------------------------------

    def subscribe(self, task: "Task") -> None:
        """Called by the executor when a task blocks on this pollable."""
        # inlined done() — this runs once per executor poll
        if self._value is not _PENDING or self._exc is not None:
            task.wake()
            return
        if task not in self._wakers:
            self._wakers.append(task)

    def __await__(self) -> Generator[Any, None, Any]:
        while self._value is _PENDING and self._exc is None:  # inlined done()
            yield self
        return self.result()


_PyFuture = Future

# Swap in the compiled Future (native/simloop.c) when available: same
# contract (state machine, FIFO wakers, __await__ yields self until
# resolved), with set_result/subscribe/__await__ running in C.  The
# schedule is unchanged — wakers fire in the same order either way.
try:
    from . import native as _native

    _simloop = _native.simloop()
except Exception:  # pragma: no cover - native tier is always optional
    _simloop = None
if _simloop is not None:
    Future = _simloop.Future  # type: ignore[misc]


class JoinHandle(Future):
    """Handle to a spawned task (sim/task/join.rs).

    ``await handle`` returns the task's return value; raises
    :class:`CancelledError` if the task was aborted/killed, or re-raises the
    task's exception if it panicked.  ``abort()`` mirrors tokio's
    ``AbortHandle::abort`` (sets the cancelled flag and wakes the task so the
    executor drops it, sim/task/mod.rs:575-655).
    """

    __slots__ = ("task",)

    def __init__(self, task: "Task"):
        super().__init__()
        self.task = task

    def abort(self) -> None:
        self.task.abort()

    def abort_handle(self) -> "JoinHandle":
        return self

    def is_finished(self) -> bool:
        return self.done()


class _Select:
    """Wait for the first of several pollables to resolve."""

    __slots__ = ("futs",)

    def __init__(self, futs: Iterable[Future]):
        self.futs = list(futs)

    def subscribe(self, task: "Task") -> None:
        for f in self.futs:
            f.subscribe(task)

    def __await__(self) -> Generator[Any, None, Any]:
        while True:
            for i, f in enumerate(self.futs):
                if f.done():
                    return i, f.result()
            yield self


def select(*futs: Future):
    """``await select(a, b, ...)`` -> ``(index, value)`` of the first done.

    Operands must be Future-like (spawn coroutines first).  The analogue of
    ``tokio::select!``; polling order is deterministic (left to right).
    """
    return _Select(futs)


class _Join:
    __slots__ = ("futs",)

    def __init__(self, futs: Iterable[Future]):
        self.futs = list(futs)

    def subscribe(self, task: "Task") -> None:
        for f in self.futs:
            if not f.done():
                f.subscribe(task)
                return

    def __await__(self) -> Generator[Any, None, Any]:
        while not all(f.done() for f in self.futs):
            yield self
        return [f.result() for f in self.futs]


def join(*futs: Future):
    """``await join(a, b, ...)`` -> list of results (tokio ``join!``)."""
    return _Join(futs)


class _PendingForever:
    def subscribe(self, task: "Task") -> None:
        pass

    def __await__(self) -> Generator[Any, None, Any]:
        while True:
            yield self


def pending_forever() -> "_PendingForever":
    """An awaitable that never resolves (``std::future::pending``)."""
    return _PendingForever()
