"""gRPC simulation shim — the madsim-tonic analogue.

The reference intercepts tonic (Rust gRPC) with a message-passing protocol
over simulated connections (madsim-tonic/src/client.rs:33-38): a request is
``(path, server_streaming, Request)``, streamed bodies travel as raw
messages, and ``()`` marks end-of-stream. This package is the same design
Python-native:

- :mod:`status` — ``Code`` + ``Status`` (the error surface of gRPC)
- :mod:`channel` — transport ``Endpoint`` builder and ``Channel`` with
  random load balancing over static (``balance_list``) or dynamic
  (``balance_channel``) endpoint sets (transport/channel.rs:228-359)
- :mod:`server` — ``Server.builder().add_service(...).serve[_with_shutdown]``
  routing by service name with an Unimplemented fallback
  (transport/server.rs:210-335)
- :mod:`client` — generic ``Grpc`` caller: unary / client-streaming /
  server-streaming / bidi + interceptors + grpc-timeout
  (client.rs:39-219)
- :mod:`service` — decorators that play the role of tonic-build codegen
  (``@service`` + ``@unary``/``@server_streaming``/…), generating both the
  server routing table and a typed client (madsim-tonic-build/src/).
"""

from .status import Code, Status
from .codec import Streaming
from .channel import Change, Channel, Endpoint
from .server import Server
from .client import Grpc, Request, Response
from .service import (
    ServiceClient,
    bidi_streaming,
    client_streaming,
    server_streaming,
    service,
    unary,
)
from .protogen import ProtoPackage, ProtogenError, compile_protos

__all__ = [
    "Change",
    "Channel",
    "Code",
    "Endpoint",
    "Grpc",
    "ProtoPackage",
    "ProtogenError",
    "Request",
    "Response",
    "Server",
    "ServiceClient",
    "Status",
    "Streaming",
    "bidi_streaming",
    "client_streaming",
    "compile_protos",
    "server_streaming",
    "service",
    "unary",
]
