"""gRPC server: builder + router + per-connection dispatch.

Mirrors madsim-tonic ``transport::Server`` (transport/server.rs:210-335):
``Server.builder().add_service(a).add_service(b).serve(addr)`` binds a sim
Endpoint, accepts connections in a loop, routes each request by the service
name parsed from the path, spawns a task per request, and falls back to
``Unimplemented`` for unknown services/methods. All four streaming shapes
are handled; handler ``Status`` errors become ``("err", Status)`` replies;
mid-stream errors become status trailers.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, Optional

from .. import task as mstask
from ..futures import Future
from ..net.endpoint import Endpoint as NetEndpoint
from .codec import EOS, ERR, Streaming
from .service import camel, method_table, service_name
from .status import Status


class Server:
    @staticmethod
    def builder() -> "ServerBuilder":
        return ServerBuilder()


class ServerBuilder:
    _router_cls: "type | None" = None  # real/grpc.py overrides

    def __init__(self) -> None:
        self._services: Dict[str, Any] = {}

    # accepted-and-ignored tuning knobs (transport/server.rs accepts ~10)
    def _ignore(self, *_a: Any, **_k: Any) -> "ServerBuilder":
        return self

    timeout = _ignore
    concurrency_limit_per_connection = _ignore
    initial_stream_window_size = _ignore
    initial_connection_window_size = _ignore
    max_concurrent_streams = _ignore
    tcp_keepalive = _ignore
    tcp_nodelay = _ignore
    http2_keepalive_interval = _ignore
    http2_keepalive_timeout = _ignore
    max_frame_size = _ignore
    accept_http1 = _ignore
    layer = _ignore

    def add_service(self, svc: Any) -> "Router":
        return (self._router_cls or Router)(self)._add(svc)

    def add_optional_service(self, svc: Optional[Any]) -> "Router":
        router = (self._router_cls or Router)(self)
        return router._add(svc) if svc is not None else router


class Router:
    """Routes by service name (transport/server.rs:258-272).

    ``_spawn`` and the serve/accept loop are the only executor-bound
    pieces; real/grpc.py subclasses override them to serve the SAME
    service classes over real TCP."""

    _spawn = staticmethod(mstask.spawn)

    def __init__(self, builder: ServerBuilder):
        self._services: Dict[str, Any] = dict(builder._services)
        #: set once the listener is bound; lets callers serve on port 0
        #: and discover the address (handy in real mode)
        self.bound_addr: Optional[tuple] = None

    def _add(self, svc: Any) -> "Router":
        self._services[service_name(svc)] = svc
        return self

    def add_service(self, svc: Any) -> "Router":
        return self._add(svc)

    async def serve(self, addr: "str | tuple") -> None:
        await self.serve_with_shutdown(addr, None)

    @staticmethod
    async def _bind(addr: "str | tuple") -> Any:
        """Listener factory (anything with accept1/close) — the one
        transport-bound step; real mode binds a StreamListener instead."""
        return await NetEndpoint.bind(addr)

    async def serve_with_shutdown(
        self, addr: "str | tuple", signal: Optional[Any]
    ) -> None:
        """Accept-loop until ``signal`` (an awaitable) resolves; ``None``
        serves forever (transport/server.rs:217-237)."""
        ep = await self._bind(addr)
        local = getattr(ep, "local_addr", None)
        self.bound_addr = local() if callable(local) else None
        accept_task = self._spawn(self._accept_loop(ep), name=f"grpc-serve {addr}")
        try:
            if signal is None:
                await accept_task
            else:
                await signal
        finally:
            accept_task.abort()
            ep.close()

    async def _accept_loop(self, ep: Any) -> None:
        while True:
            tx, rx, _src = await ep.accept1()
            self._spawn(self._serve_conn(tx, rx), name="grpc-conn")

    async def _serve_conn(self, tx: Any, rx: Any) -> None:
        try:
            head = await rx.recv()
        except ConnectionResetError:
            return
        if head is None:
            return
        path, server_streaming, request = head
        svc_name, _, method_path = path.strip("/").partition("/")
        svc = self._services.get(svc_name)
        handler = None
        kind = None
        if svc is not None:
            table = method_table(svc)
            for name, k in table.items():
                if method_path in (name, camel(name)):
                    handler, kind = getattr(svc, name), k
                    break
        if handler is None:
            try:
                await tx.send(("err", Status.unimplemented(f"unknown path {path}")))
            except BrokenPipeError:
                pass
            tx.close()
            return
        # task per request (transport/server.rs:275-333)
        self._spawn(
            self._dispatch(kind, handler, request, tx, rx),
            name=f"grpc-handle {path}",
        )

    @staticmethod
    async def _dispatch(kind: str, handler: Any, request: Any, tx: Any, rx: Any) -> None:
        try:
            if kind == "unary":
                result = await handler(request)
                await tx.send(("ok", _into_response(result)))
            elif kind == "client_streaming":
                result = await handler(Streaming(rx))
                await tx.send(("ok", _into_response(result)))
            elif kind == "server_streaming":
                agen = handler(request)
                await _serve_stream(tx, agen)
                return
            else:  # bidi
                agen = handler(Streaming(rx))
                await _serve_stream(tx, agen)
                return
        except Status as st:
            try:
                await tx.send(("err", st))
            except BrokenPipeError:
                pass
        except (BrokenPipeError, ConnectionResetError):
            pass  # client (or our node's route to it) went away mid-call
        finally:
            tx.close()


def _into_response(result: Any) -> Any:
    from .client import Response

    return result if isinstance(result, Response) else Response(result)


async def _serve_stream(tx: Any, agen: Any) -> None:
    """Send ok-head, then the stream body, then the EOS trailer; a Status
    raised mid-stream becomes a status trailer (server.rs:300-333)."""
    from .client import Response

    if inspect.iscoroutine(agen):
        agen = await agen  # handler returned an awaitable of an iterator
    try:
        await tx.send(("ok", Response(None)))
        if hasattr(agen, "__aiter__"):
            async for msg in agen:
                await tx.send(msg)
        else:
            for msg in agen:
                await tx.send(msg)
        await tx.send(EOS)
    except Status as st:
        try:
            await tx.send((ERR, st))
        except BrokenPipeError:
            pass
    except BrokenPipeError:
        pass
