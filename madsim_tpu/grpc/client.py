"""Generic gRPC client: the four call shapes + interceptors + timeouts.

Mirrors madsim-tonic ``client::Grpc`` (client.rs:39-219). The wire exchange
per call (client.rs:33-38):

    head:  (path, server_streaming, Request)       client -> server
    body:  raw messages then EOS                   (client-streaming calls)
    reply: ("ok", Response) | ("err", Status)      server -> client
    body:  raw messages then EOS                   (server-streaming calls)

Transport failures surface as ``Status.unavailable`` (the reference maps
broken connections the same way — a killed server mid-call yields
"broken pipe" on send and Unavailable on the next call,
tonic-example/tests/test.rs:234-278).
"""

from __future__ import annotations

from typing import Any, AsyncIterable, Callable, Dict, Iterable, Optional, Tuple, Union

from .. import task as mstask
from .. import time as mstime
from .channel import Channel
from .codec import EOS, Streaming, is_err, is_eos
from .status import Status


class Request:
    """A request envelope: message + metadata + optional timeout (the
    tonic ``Request<T>`` with grpc-timeout metadata support)."""

    def __init__(self, message: Any = None, metadata: Optional[Dict[str, str]] = None,
                 timeout: Optional[float] = None):
        self.message = message
        self.metadata: Dict[str, str] = dict(metadata or {})
        if timeout is not None:
            self.set_timeout(timeout)

    def set_timeout(self, seconds: float) -> None:
        # encoded like the grpc-timeout header so interceptors can see it
        self.metadata["grpc-timeout"] = f"{int(seconds * 1000)}m"

    def timeout(self) -> Optional[float]:
        v = self.metadata.get("grpc-timeout")
        if v is None:
            return None
        unit = v[-1]
        n = float(v[:-1])
        return n * {"H": 3600, "M": 60, "S": 1, "m": 1e-3, "u": 1e-6, "n": 1e-9}[unit]

    def get_ref(self) -> Any:
        return self.message

    def into_inner(self) -> Any:
        return self.message

    @staticmethod
    def wrap(msg: Any) -> "Request":
        return msg if isinstance(msg, Request) else Request(msg)


class Response:
    """The response envelope (tonic ``Response<T>``)."""

    def __init__(self, message: Any = None, metadata: Optional[Dict[str, str]] = None):
        self.message = message
        self.metadata: Dict[str, str] = dict(metadata or {})

    def get_ref(self) -> Any:
        return self.message

    def into_inner(self) -> Any:
        return self.message


Interceptor = Callable[[Request], Request]


async def _feed(tx: Any, messages: Union[Iterable, AsyncIterable]) -> None:
    """Send a client-side request stream then the EOS trailer."""
    try:
        if hasattr(messages, "__aiter__"):
            async for m in messages:
                await tx.send(m)
        else:
            for m in messages:
                await tx.send(m)
        await tx.send(EOS)
    except BrokenPipeError:
        pass  # server went away; the reply read surfaces the error


class Grpc:
    """The generic caller; typed clients (service.py) wrap this.

    The executor bindings are class attributes so the real-mode twin
    (real/grpc.py) can swap sim spawn/timeout for asyncio ones while
    reusing every call shape unchanged — the analogue of the reference
    compiling the same tonic surface with or without ``--cfg madsim``.
    """

    _spawn = staticmethod(mstask.spawn)
    _timeout = staticmethod(mstime.timeout)
    _timeout_error: type = mstime.TimeoutError

    def __init__(self, channel: Channel, interceptor: Optional[Interceptor] = None):
        self.channel = channel
        self.interceptor = interceptor

    def with_interceptor(self, f: Interceptor) -> "Grpc":
        return type(self)(self.channel, f)  # keep real-mode subclass bindings

    def _prepare(self, request: Request) -> Request:
        if self.interceptor is not None:
            request = self.interceptor(request)
        if request.timeout() is None and self.channel.default_timeout is not None:
            request.set_timeout(self.channel.default_timeout)
        return request

    async def _call(self, path: str, request: Request, server_streaming: bool,
                    body: Optional[Union[Iterable, AsyncIterable]]) -> Tuple[Any, Any]:
        """One exchange; returns (reply_head, rx)."""
        try:
            tx, rx = await self.channel.open_stream()
        except (ConnectionError, OSError) as e:
            raise Status.unavailable(f"transport error: {e}") from None
        try:
            try:
                await tx.send((path, server_streaming, request))
            except BrokenPipeError as e:
                raise Status.unavailable(f"broken pipe: {e}") from None
            if body is not None:
                self._spawn(_feed(tx, body), name=f"grpc-feed {path}")
            else:
                tx.close()
            try:
                head = await rx.recv()
            except ConnectionResetError as e:
                raise Status.unavailable(str(e) or "connection reset") from None
            if head is None:
                raise Status.unavailable("connection closed before response")
            return head, rx
        except BaseException:
            # error OR cancellation (e.g. a grpc-timeout cancelling this
            # call mid-await): drop both halves so the real-mode socket is
            # freed instead of leaking until GC
            tx.close()
            rx.close()
            raise

    async def _call_timeout(self, path: str, request: Request,
                            server_streaming: bool, body) -> Tuple[Any, Any]:
        timeout_s = request.timeout()
        if timeout_s is None:
            return await self._call(path, request, server_streaming, body)
        try:
            return await self._timeout(
                timeout_s, self._call(path, request, server_streaming, body)
            )
        except self._timeout_error:
            raise Status.cancelled("Timeout expired") from None

    @staticmethod
    def _unwrap(head: Any) -> Response:
        kind, payload = head
        if kind == "err":
            raise payload
        return payload

    # -- the four call shapes (client.rs:52-219) ---------------------------

    async def unary(self, path: str, request: Union[Request, Any]) -> Response:
        request = self._prepare(Request.wrap(request))
        head, rx = await self._call_timeout(path, request, False, None)
        try:
            return self._unwrap(head)
        finally:
            rx.close()  # exchange complete; frees the real-mode socket

    async def client_streaming(
        self, path: str, messages: Union[Iterable, AsyncIterable],
        request: Optional[Request] = None,
    ) -> Response:
        request = self._prepare(request or Request())
        head, rx = await self._call_timeout(path, request, False, messages)
        try:
            return self._unwrap(head)
        finally:
            rx.close()

    async def server_streaming(
        self, path: str, request: Union[Request, Any]
    ) -> Streaming:
        request = self._prepare(Request.wrap(request))
        head, rx = await self._call_timeout(path, request, True, None)
        try:
            self._unwrap(head)
        except BaseException:
            rx.close()
            raise
        return Streaming(rx, close_at_end=True)

    async def streaming(
        self, path: str, messages: Union[Iterable, AsyncIterable],
        request: Optional[Request] = None,
    ) -> Streaming:
        request = self._prepare(request or Request())
        head, rx = await self._call_timeout(path, request, True, messages)
        try:
            self._unwrap(head)
        except BaseException:
            rx.close()
            raise
        return Streaming(rx, close_at_end=True)
