"""gRPC status codes and the Status error (the tonic ``Status`` surface)."""

from __future__ import annotations

from enum import IntEnum


class Code(IntEnum):
    """Canonical gRPC status codes."""

    OK = 0
    CANCELLED = 1
    UNKNOWN = 2
    INVALID_ARGUMENT = 3
    DEADLINE_EXCEEDED = 4
    NOT_FOUND = 5
    ALREADY_EXISTS = 6
    PERMISSION_DENIED = 7
    RESOURCE_EXHAUSTED = 8
    FAILED_PRECONDITION = 9
    ABORTED = 10
    OUT_OF_RANGE = 11
    UNIMPLEMENTED = 12
    INTERNAL = 13
    UNAVAILABLE = 14
    DATA_LOSS = 15
    UNAUTHENTICATED = 16


class Status(Exception):
    """A gRPC error — raised by clients, returned by handlers that fail.

    Mirrors tonic ``Status`` (constructor-per-code API).
    """

    def __init__(self, code: Code, message: str = ""):
        self.code = Code(code)
        self.message = message
        super().__init__(f"status: {self.code.name}, message: {message!r}")

    def __str__(self) -> str:
        # derived from the fields, not Exception.args, so a Status decoded
        # from the wire (real/codec.py skips __init__) still prints fully
        return f"status: {Code(self.code).name}, message: {self.message!r}"

    # tonic-style constructors ------------------------------------------------

    @classmethod
    def ok(cls, msg: str = "") -> "Status":
        return cls(Code.OK, msg)

    @classmethod
    def cancelled(cls, msg: str = "") -> "Status":
        return cls(Code.CANCELLED, msg)

    @classmethod
    def unknown(cls, msg: str = "") -> "Status":
        return cls(Code.UNKNOWN, msg)

    @classmethod
    def invalid_argument(cls, msg: str = "") -> "Status":
        return cls(Code.INVALID_ARGUMENT, msg)

    @classmethod
    def deadline_exceeded(cls, msg: str = "") -> "Status":
        return cls(Code.DEADLINE_EXCEEDED, msg)

    @classmethod
    def not_found(cls, msg: str = "") -> "Status":
        return cls(Code.NOT_FOUND, msg)

    @classmethod
    def already_exists(cls, msg: str = "") -> "Status":
        return cls(Code.ALREADY_EXISTS, msg)

    @classmethod
    def permission_denied(cls, msg: str = "") -> "Status":
        return cls(Code.PERMISSION_DENIED, msg)

    @classmethod
    def resource_exhausted(cls, msg: str = "") -> "Status":
        return cls(Code.RESOURCE_EXHAUSTED, msg)

    @classmethod
    def failed_precondition(cls, msg: str = "") -> "Status":
        return cls(Code.FAILED_PRECONDITION, msg)

    @classmethod
    def aborted(cls, msg: str = "") -> "Status":
        return cls(Code.ABORTED, msg)

    @classmethod
    def out_of_range(cls, msg: str = "") -> "Status":
        return cls(Code.OUT_OF_RANGE, msg)

    @classmethod
    def unimplemented(cls, msg: str = "") -> "Status":
        return cls(Code.UNIMPLEMENTED, msg)

    @classmethod
    def internal(cls, msg: str = "") -> "Status":
        return cls(Code.INTERNAL, msg)

    @classmethod
    def unavailable(cls, msg: str = "") -> "Status":
        return cls(Code.UNAVAILABLE, msg)

    @classmethod
    def data_loss(cls, msg: str = "") -> "Status":
        return cls(Code.DATA_LOSS, msg)

    @classmethod
    def unauthenticated(cls, msg: str = "") -> "Status":
        return cls(Code.UNAUTHENTICATED, msg)
