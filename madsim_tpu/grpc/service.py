"""Service definition decorators + typed client — the codegen analogue.

The reference generates sim clients/servers from .proto files with a forked
tonic-build (madsim-tonic-build/src/{client,server}.rs). A Python framework
needs no build step: decorate a class and its handler methods, and
``ServiceClient`` derives the typed client with the right call shape per
method:

    @grpc.service("helloworld.Greeter")
    class Greeter:
        @grpc.unary
        async def say_hello(self, request): ...
        @grpc.server_streaming
        async def lots_of_replies(self, request): yield ...
        @grpc.client_streaming
        async def lots_of_greetings(self, stream): ...
        @grpc.bidi_streaming
        async def bidi_hello(self, stream): yield ...

    client = grpc.ServiceClient(Greeter, channel)
    reply = (await client.say_hello(HelloRequest(...))).into_inner()

Paths are ``/<service>/<Method>`` with tonic's CamelCase method segment, so
routing matches what the reference's generated code produces.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .channel import Channel
from .status import Status

_KIND_ATTR = "__grpc_kind__"
_NAME_ATTR = "__grpc_service_name__"
_TABLE_ATTR = "__grpc_methods__"
# protogen-attached: snake method name -> (request message class, response
# message class). Only proto-derived services carry it; the grpcio interop
# layer (real/grpc.py) needs it for wire serialization.
_IO_ATTR = "__grpc_io__"
# protogen-attached: snake method name -> the LITERAL proto method name.
# camel() does not round-trip acronym names (GetTPUInfo -> get_tpu_info ->
# GetTpuInfo), and a stock gRPC peer uses the descriptor's literal name in
# the wire path, so the grpcio tier must too.
_WIRE_ATTR = "__grpc_wire_names__"


def camel(snake: str) -> str:
    return "".join(p.title() for p in snake.split("_"))


def unary(fn: Callable) -> Callable:
    setattr(fn, _KIND_ATTR, "unary")
    return fn


def client_streaming(fn: Callable) -> Callable:
    setattr(fn, _KIND_ATTR, "client_streaming")
    return fn


def server_streaming(fn: Callable) -> Callable:
    setattr(fn, _KIND_ATTR, "server_streaming")
    return fn


def bidi_streaming(fn: Callable) -> Callable:
    setattr(fn, _KIND_ATTR, "bidi_streaming")
    return fn


def service(name: str) -> Callable[[type], type]:
    """Class decorator: registers the gRPC service name + method table."""

    def deco(cls: type) -> type:
        table: Dict[str, str] = {}
        for attr in dir(cls):
            v = getattr(cls, attr, None)
            kind = getattr(v, _KIND_ATTR, None)
            if kind is not None:
                table[attr] = kind
        setattr(cls, _NAME_ATTR, name)
        setattr(cls, _TABLE_ATTR, table)
        return cls

    return deco


def service_name(svc: Any) -> str:
    name = getattr(svc, _NAME_ATTR, None)
    if name is None:
        raise TypeError(f"{type(svc).__name__} is not a @grpc.service class")
    return name


def method_table(svc: Any) -> Dict[str, str]:
    return getattr(svc, _TABLE_ATTR, {})


class ServiceClient:
    """Typed client for a @service class (the generated-client analogue).

    Every decorated method becomes an attribute with the matching call
    shape; unary/server-streaming take a message (or Request),
    client-streaming/bidi take an iterable or async iterable of messages.
    """

    _grpc_cls: "type | None" = None  # real/grpc.py overrides

    def __init__(self, service_cls: type, channel: Channel,
                 interceptor: Optional[Callable] = None):
        from .client import Grpc

        self._cls = service_cls
        self._name = getattr(service_cls, _NAME_ATTR)
        self._table = getattr(service_cls, _TABLE_ATTR)
        self._grpc = (type(self)._grpc_cls or Grpc)(channel, interceptor)

    @classmethod
    def with_interceptor(cls, service_cls: type, channel: Channel,
                         interceptor: Callable) -> "ServiceClient":
        return cls(service_cls, channel, interceptor)

    def _path(self, method: str) -> str:
        return f"/{self._name}/{camel(method)}"

    def __getattr__(self, method: str) -> Callable:
        kind = self._table.get(method)
        if kind is None:
            raise AttributeError(f"{self._name} has no rpc method {method!r}")
        path = self._path(method)
        grpc = self._grpc
        if kind == "unary":
            return lambda msg: grpc.unary(path, msg)
        if kind == "server_streaming":
            return lambda msg: grpc.server_streaming(path, msg)
        if kind == "client_streaming":
            return lambda msgs: grpc.client_streaming(path, msgs)
        return lambda msgs: grpc.streaming(path, msgs)
