"""``.proto`` ingestion for the gRPC shim — the madsim-tonic-build analogue.

The reference forks tonic's codegen so one ``.proto`` produces BOTH real
stubs and sim stubs (madsim-tonic-build/src/prost.rs:599-680: the sim
``ServiceGenerator`` writes into ``$OUT_DIR/sim/`` next to the real
tonic-build output). Python needs no build step, so the same capability
is a runtime call:

    pkg = grpc.compile_protos("helloworld.proto")

    HelloRequest = pkg.messages["helloworld.HelloRequest"]   # real protobufs

    @pkg.implement("helloworld.Greeter")                     # server side
    class Greeter:
        async def say_hello(self, request): ...              # kinds from the proto
        async def lots_of_replies(self, request): yield ...

    client = pkg.client("helloworld.Greeter", channel)       # typed client
    reply = (await client.say_hello(HelloRequest(name="x"))).into_inner()

``compile_protos`` shells out to ``protoc`` (baked into the image) for a
descriptor set + ``--python_out`` message modules: message classes are
REAL ``google.protobuf`` messages, method streaming kinds come from the
descriptor's client/server streaming flags, and the generated stubs speak
this shim's message protocol — so a user with an existing proto tree gets
clients/servers wired into the simulator without hand-decorating anything.
"""

from __future__ import annotations

import hashlib
import importlib.util
import os
import re
import subprocess
import sys
import tempfile
from typing import Any, Callable, Dict, NamedTuple, Optional

# import the submodule's names directly: the package __init__ rebinds the
# `service` attribute to the decorator function, so `from . import
# service` would grab that instead of the module
from .service import (
    _IO_ATTR,
    _KIND_ATTR,
    _NAME_ATTR,
    _TABLE_ATTR,
    _WIRE_ATTR,
    ServiceClient,
    service as _service_decorator,
)
from .channel import Channel


class ProtogenError(Exception):
    """protoc failed or the descriptor set is unusable."""


# generated-module content seen per module name: recompiling a *modified*
# proto under the same filename must not silently hand back the first
# compile's stale classes (it would also mask descriptor-pool conflicts)
_COMPILED_SHA: Dict[str, str] = {}


class ServiceSpec(NamedTuple):
    full_name: str
    methods: Dict[str, str]  # python snake_case name -> call kind
    #: snake_case name -> (request type full name, response type full name);
    #: resolved to message classes on demand (grpcio interop needs them)
    io: Dict[str, tuple] = {}
    #: snake_case name -> literal proto method name (wire-path segment for
    #: stock-gRPC peers; camel() does not round-trip acronym names)
    wire: Dict[str, str] = {}


def _snake(name: str) -> str:
    """CamelCase proto method name -> python snake_case (tonic's mapping
    in reverse; ``service.camel`` round-trips it for the wire path)."""
    s = re.sub(r"(?<=[a-z0-9])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])", "_", name)
    return s.lower()


def _kind(method) -> str:
    if method.client_streaming and method.server_streaming:
        return "bidi_streaming"
    if method.client_streaming:
        return "client_streaming"
    if method.server_streaming:
        return "server_streaming"
    return "unary"


class ProtoPackage:
    """Everything one ``compile_protos`` call produced."""

    def __init__(self, services: Dict[str, ServiceSpec],
                 messages: Dict[str, type], modules: Dict[str, Any]):
        self.services = services
        self.messages = messages  # proto full name -> message class
        self.modules = modules  # generated module name -> module

    # -- server side --------------------------------------------------------

    def implement(self, full_name: str) -> Callable[[type], type]:
        """Class decorator: attach the proto-declared kind to each handler
        and register the service (the generated-server analogue). The
        class must define one ``async def`` per rpc, snake_case named."""
        spec = self._spec(full_name)

        def deco(cls: type) -> type:
            for snake, kind in spec.methods.items():
                fn = cls.__dict__.get(snake)
                if fn is None:
                    raise ProtogenError(
                        f"{cls.__name__} is missing rpc method {snake!r} "
                        f"declared by {full_name} in the proto"
                    )
                setattr(fn, _KIND_ATTR, kind)
            cls = _service_decorator(full_name)(cls)
            setattr(cls, _IO_ATTR, self._io_classes(spec))
            setattr(cls, _WIRE_ATTR, dict(spec.wire))
            return cls

        return deco

    # -- client side --------------------------------------------------------

    def client(self, full_name: str, channel: Channel,
               interceptor: Optional[Callable] = None) -> ServiceClient:
        """Typed client built from the proto alone — no server class
        needed in-process (the generated-client analogue)."""
        return ServiceClient(self.stub(full_name), channel, interceptor)

    def stub(self, full_name: str) -> type:
        """A class carrying the service's name, method table, and message
        types — what ``ServiceClient`` (sim or grpcio-backed) needs to
        derive a typed client without a server class in-process."""
        spec = self._spec(full_name)
        return type(
            spec.full_name.rsplit(".", 1)[-1] + "Stub",
            (),
            {
                _NAME_ATTR: spec.full_name,
                _TABLE_ATTR: dict(spec.methods),
                _IO_ATTR: self._io_classes(spec),
                _WIRE_ATTR: dict(spec.wire),
            },
        )

    def _io_classes(self, spec: ServiceSpec) -> Dict[str, tuple]:
        """snake method name -> (request class, response class). Methods
        whose types didn't resolve (e.g. nested message types) are
        omitted — the sim transport doesn't need them; the grpcio interop
        layer reports the gap by name if such a method is ever called."""
        out: Dict[str, tuple] = {}
        for snake, (req_name, rsp_name) in spec.io.items():
            req = self.messages.get(req_name)
            rsp = self.messages.get(rsp_name)
            if req is not None and rsp is not None:
                out[snake] = (req, rsp)
        return out

    def _spec(self, full_name: str) -> ServiceSpec:
        spec = self.services.get(full_name)
        if spec is None:
            known = ", ".join(sorted(self.services)) or "<none>"
            raise ProtogenError(
                f"unknown service {full_name!r}; protos defined: {known}"
            )
        return spec


def compile_protos(*protos: str, includes: tuple = ()) -> ProtoPackage:
    """Compile ``.proto`` files into a :class:`ProtoPackage`.

    Runs ``protoc`` twice-in-one: ``--descriptor_set_out`` (service and
    method metadata) and ``--python_out`` (real message classes, loaded
    from a temp dir and registered under their generated module names so
    cross-file imports in multi-proto trees resolve)."""
    proto_paths = [os.path.abspath(p) for p in protos]
    for p in proto_paths:
        if not os.path.exists(p):
            raise ProtogenError(f"no such proto file: {p}")
    inc = {os.path.dirname(p) for p in proto_paths}
    inc.update(os.path.abspath(i) for i in includes)

    with tempfile.TemporaryDirectory() as tmp:
        ds_path = os.path.join(tmp, "descriptors.pb")
        cmd = [
            "protoc",
            f"--descriptor_set_out={ds_path}",
            "--include_imports",
            f"--python_out={tmp}",
            *[f"-I{i}" for i in sorted(inc)],
            *proto_paths,
        ]
        run = subprocess.run(cmd, capture_output=True, text=True)
        if run.returncode != 0:
            raise ProtogenError(f"protoc failed:\n{run.stderr.strip()}")

        from google.protobuf import descriptor_pb2

        ds = descriptor_pb2.FileDescriptorSet()
        with open(ds_path, "rb") as f:
            ds.ParseFromString(f.read())

        modules: Dict[str, Any] = {}
        services: Dict[str, ServiceSpec] = {}
        messages: Dict[str, type] = {}
        for fd in ds.file:
            mod_name = fd.name[: -len(".proto")].replace("/", ".").replace(
                "-", "_"
            ) + "_pb2"
            mod_path = os.path.join(tmp, fd.name[: -len(".proto")] + "_pb2.py")
            if os.path.exists(mod_path):
                with open(mod_path, "rb") as f:
                    sha = hashlib.sha256(f.read()).hexdigest()
                if mod_name in sys.modules:
                    prev = _COMPILED_SHA.get(mod_name)
                    if prev is None:
                        # loaded outside compile_protos (e.g. an installed
                        # _pb2): trust it only if its descriptor bytes match
                        # what protoc just generated
                        loaded = sys.modules[mod_name]
                        ser = getattr(
                            getattr(loaded, "DESCRIPTOR", None),
                            "serialized_pb",
                            None,
                        )
                        # compare parsed messages, not bytes: a different
                        # protoc release can serialize the same descriptor
                        # with different bytes
                        same = ser is not None and (
                            descriptor_pb2.FileDescriptorProto.FromString(ser)
                            == fd
                        )
                        if not same:
                            raise ProtogenError(
                                f"module {mod_name!r} is already loaded with "
                                f"a different descriptor than {fd.name!r} "
                                "compiles to; rename the file or restart — "
                                "protobuf's descriptor pool cannot hold two "
                                "versions of one file"
                            )
                        _COMPILED_SHA[mod_name] = sha
                    elif prev != sha:
                        raise ProtogenError(
                            f"proto {fd.name!r} changed since it was first "
                            f"compiled in this process (module {mod_name!r} "
                            "is already loaded with different contents); "
                            "rename the file or restart the process — "
                            "protobuf's descriptor pool cannot hold two "
                            "versions of one file"
                        )
                    modules[mod_name] = sys.modules[mod_name]
                else:
                    spec = importlib.util.spec_from_file_location(
                        mod_name, mod_path
                    )
                    module = importlib.util.module_from_spec(spec)
                    # registered BEFORE exec so sibling _pb2 imports resolve
                    sys.modules[mod_name] = module
                    try:
                        spec.loader.exec_module(module)
                    except Exception:
                        del sys.modules[mod_name]
                        raise
                    _COMPILED_SHA[mod_name] = sha
                    modules[mod_name] = module
            elif mod_name in sys.modules:
                modules[mod_name] = sys.modules[mod_name]

            pkg = fd.package
            module = modules.get(mod_name)
            for msg in fd.message_type:
                full = f"{pkg}.{msg.name}" if pkg else msg.name
                if module is not None and hasattr(module, msg.name):
                    messages[full] = getattr(module, msg.name)
            for svc in fd.service:
                full = f"{pkg}.{svc.name}" if pkg else svc.name
                services[full] = ServiceSpec(
                    full_name=full,
                    methods={_snake(m.name): _kind(m) for m in svc.method},
                    # descriptor type refs are ".pkg.Msg"-qualified
                    io={
                        _snake(m.name): (
                            m.input_type.lstrip("."),
                            m.output_type.lstrip("."),
                        )
                        for m in svc.method
                    },
                    wire={_snake(m.name): m.name for m in svc.method},
                )

        return ProtoPackage(services, messages, modules)
