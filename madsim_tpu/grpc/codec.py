"""Streaming message adapter (the tonic ``Streaming<T>`` analogue,
madsim-tonic/src/codec.rs).

Wire protocol (madsim-tonic/src/client.rs:33-38): stream bodies travel as
raw messages on the connection; ``()`` — here ``EOS`` — marks end of
stream; a mid-stream server error arrives as an ``("__status__", Status)``
trailer.
"""

from __future__ import annotations

from typing import Any, Optional

from .status import Status

EOS = ("__eos__",)  # end-of-stream marker (the reference's `()` trailer)
ERR = "__status__"


def is_eos(msg: Any) -> bool:
    return isinstance(msg, tuple) and len(msg) == 1 and msg == EOS


def is_err(msg: Any) -> bool:
    return isinstance(msg, tuple) and len(msg) == 2 and msg[0] == ERR


class Streaming:
    """Async iterator over a stream of response messages.

    ``async for msg in stream`` or ``await stream.message()`` (returns
    ``None`` at end of stream — the tonic API shape).
    """

    def __init__(self, rx: Any, close_at_end: bool = False):
        # close_at_end is set on CLIENT-side response streams only: once the
        # stream finishes the whole exchange is over, so the receiver half
        # can be dropped (in real mode this frees the TCP socket).  Server-
        # side request streams share their connection with the pending
        # reply, so they must NOT close it.
        self._rx = rx
        self._done = False
        self._close_at_end = close_at_end

    def _finish(self) -> None:
        self._done = True
        if self._close_at_end:
            close = getattr(self._rx, "close", None)
            if close is not None:
                close()

    async def message(self) -> Optional[Any]:
        if self._done:
            return None
        try:
            msg = await self._rx.recv()
        except ConnectionResetError as e:
            self._done = True
            raise Status.unavailable(str(e) or "connection reset") from None
        if msg is None or is_eos(msg):
            self._finish()
            return None
        if is_err(msg):
            self._finish()
            raise msg[1]
        return msg

    def close(self) -> None:
        """Drop the response stream mid-flight: closes the underlying
        connection half, so the server's next send observes
        BrokenPipeError (the analogue of dropping tonic's ``Streaming``
        — ref tonic-example/tests/test.rs:205-232; explicit because GC
        time is nondeterministic in a determinism framework)."""
        self._done = True
        close = getattr(self._rx, "close", None)
        if close is not None:
            close()

    def __aiter__(self) -> "Streaming":
        return self

    async def __anext__(self) -> Any:
        msg = await self.message()
        if msg is None:
            raise StopAsyncIteration
        return msg
