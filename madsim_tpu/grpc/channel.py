"""Client transport: Endpoint builder + load-balanced Channel.

Mirrors madsim-tonic ``transport::{Endpoint, Channel}``
(transport/channel.rs:113-359): the Endpoint builder honors ``timeout`` and
``connect_timeout`` and *accepts-and-ignores* the HTTP2/TCP tuning knobs
(they have no meaning on a simulated link); ``Channel`` picks a random
endpoint per call (``balance_list``) and supports a dynamic endpoint set
fed through a channel (``balance_channel`` — Change::Insert/Remove).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .. import rand as msrand
from .. import time as mstime
from ..net.endpoint import connect1_ephemeral
from .status import Status


class Endpoint:
    """Builder for one server address (tonic ``transport::Endpoint``).

    ``_channel_cls`` / ``_timeout`` / ``_timeout_error`` are overridden by
    the real-mode twin (real/grpc.py) to bind the same builder surface to
    asyncio + real sockets."""

    _channel_cls: "type | None" = None  # defaults to Channel below
    _timeout_fn = staticmethod(mstime.timeout)
    _timeout_error: type = mstime.TimeoutError

    def __init__(self, uri: str):
        self.uri = uri
        self._timeout: Optional[float] = None
        self._connect_timeout: Optional[float] = None

    @classmethod
    def from_static(cls, uri: str) -> "Endpoint":
        return cls(uri)

    @classmethod
    def from_shared(cls, uri: str) -> "Endpoint":
        return cls(uri)

    def timeout(self, seconds: float) -> "Endpoint":
        """Per-RPC timeout applied to every call on the channel
        (transport/channel.rs:129-135)."""
        self._timeout = seconds
        return self

    def connect_timeout(self, seconds: float) -> "Endpoint":
        self._connect_timeout = seconds
        return self

    # accepted-and-ignored knobs (transport/channel.rs:137-188): they tune
    # a real HTTP/2 stack the simulator doesn't have
    def _ignore(self, *_a: Any, **_k: Any) -> "Endpoint":
        return self

    concurrency_limit = _ignore
    rate_limit = _ignore
    initial_stream_window_size = _ignore
    initial_connection_window_size = _ignore
    tcp_keepalive = _ignore
    tcp_nodelay = _ignore
    http2_keep_alive_interval = _ignore
    keep_alive_timeout = _ignore
    keep_alive_while_idle = _ignore
    http2_adaptive_window = _ignore
    http2_max_header_list_size = _ignore
    buffer_size = _ignore
    executor = _ignore
    user_agent = _ignore
    origin = _ignore
    tls_config = _ignore

    def _addr(self) -> str:
        uri = self.uri
        for scheme in ("http://", "https://", "grpc://"):
            if uri.startswith(scheme):
                uri = uri[len(scheme):]
        return uri.rstrip("/")

    async def connect(self) -> "Channel":
        """Verify the server is reachable, then return a channel
        (connect_timeout honored; Unavailable on failure)."""
        ch = self.connect_lazy()
        try:
            if self._connect_timeout is not None:
                tx, rx = await self._timeout_fn(self._connect_timeout, ch._open(self._addr()))
            else:
                tx, rx = await ch._open(self._addr())
            tx.close()
            rx.close()
        except self._timeout_error:
            raise Status.unavailable(f"connect timed out: {self.uri}") from None
        except (ConnectionError, OSError) as e:
            raise Status.unavailable(f"transport error: {e}") from None
        return ch

    def connect_lazy(self) -> "Channel":
        return (self._channel_cls or Channel)([self])


class Change:
    """Endpoint-set mutation for ``balance_channel`` (tower discover)."""

    @staticmethod
    def insert(key: str, endpoint: "Endpoint") -> Tuple[str, str, "Endpoint"]:
        return ("insert", key, endpoint)

    @staticmethod
    def remove(key: str) -> Tuple[str, str, None]:
        return ("remove", key, None)


class Channel:
    """A (possibly load-balanced) virtual connection to servers.

    Per call: pick a random endpoint (the reference balances randomly —
    transport/channel.rs:294-307) and open a fresh sim connection.
    """

    def __init__(self, endpoints: List[Endpoint]):
        self._endpoints: Dict[str, Endpoint] = {
            str(i): ep for i, ep in enumerate(endpoints)
        }

    @classmethod
    def balance_list(cls, endpoints: List[Endpoint]) -> "Channel":
        return cls(list(endpoints))

    @classmethod
    def balance_channel(cls, capacity: int = 16) -> Tuple["Channel", "_BalanceSender"]:
        """Dynamic endpoint set: returns (channel, sender); feed the sender
        ``Change.insert/remove`` items (transport/channel.rs:335-359)."""
        ch = cls([])
        return ch, _BalanceSender(ch)

    @property
    def default_timeout(self) -> Optional[float]:
        for ep in self._endpoints.values():
            if ep._timeout is not None:
                return ep._timeout
        return None

    @staticmethod
    def _randint(n: int) -> int:
        """Balance draw — sim RNG here; real mode overrides with ``random``."""
        return msrand.gen_range(0, n)

    def _pick(self) -> Endpoint:
        if not self._endpoints:
            raise Status.unavailable("no endpoints available")
        keys = sorted(self._endpoints)
        key = keys[self._randint(len(keys))]
        return self._endpoints[key]

    async def _open(self, addr: str):
        """Open one sim connection (ephemeral source port, released on
        establishment)."""
        try:
            return await connect1_ephemeral(addr)
        except (ConnectionError, OSError) as e:
            raise Status.unavailable(f"transport error: {e}") from None

    async def open_stream(self):
        """(tx, rx) to a randomly balanced endpoint."""
        return await self._open(self._pick()._addr())


class _BalanceSender:
    """The sender half of ``balance_channel``."""

    def __init__(self, channel: Channel):
        self._channel = channel

    async def send(self, change: Tuple[str, str, Optional[Endpoint]]) -> None:
        op, key, ep = change
        if op == "insert" and ep is not None:
            self._channel._endpoints[key] = ep
        else:
            self._channel._endpoints.pop(key, None)
