"""The etcd v3 and S3 wire servers, driven by stock clients — the same
state machines the simulator tests, reachable over their REAL protocols
(docs/real_mode.md).

Run:  python examples/wire_servers.py

- etcd: a stock gRPC client Puts, Txns, and opens a live Watch at
  /etcdserverpb.{KV,Watch}.
- S3: a stock HTTP client creates a bucket, uploads, and lists at
  path-style REST endpoints (curl works too — see the printed commands).
"""

from __future__ import annotations

import asyncio
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from madsim_tpu import real
from madsim_tpu.etcd import wire as etcd_wire
from madsim_tpu.s3 import wire as s3_wire


async def etcd_demo() -> None:
    from grpc import aio as grpc_aio

    server = etcd_wire.WireServer()
    task = real.spawn(server.serve(("127.0.0.1", 0)))
    while server.bound_addr is None:
        if task.done():
            task.result()  # surface bind failures instead of hanging
        await real.sleep(0.005)
    host, port = server.bound_addr
    print(f"etcd v3 gRPC serving on {host}:{port}")

    m = {n.rsplit(".", 1)[-1]: c
         for n, c in etcd_wire.wire_pkg().messages.items()}
    async with grpc_aio.insecure_channel(f"{host}:{port}") as ch:
        put = ch.unary_unary(
            "/etcdserverpb.KV/Put",
            request_serializer=m["PutRequest"].SerializeToString,
            response_deserializer=m["PutResponse"].FromString,
        )
        watch = ch.stream_stream(
            "/etcdserverpb.Watch/Watch",
            request_serializer=m["WatchRequest"].SerializeToString,
            response_deserializer=m["WatchResponse"].FromString,
        )
        q: asyncio.Queue = asyncio.Queue()

        async def reqs():
            while True:
                r = await q.get()
                if r is None:
                    return
                yield r

        it = watch(reqs()).__aiter__()
        await q.put(m["WatchRequest"](
            create_request=m["WatchCreateRequest"](key=b"app/",
                                                   range_end=b"app0")
        ))
        created = await it.__anext__()
        print(f"  watch created (id {created.watch_id})")
        r = await put(m["PutRequest"](key=b"app/config", value=b"v1"))
        print(f"  put app/config at revision {r.header.revision}")
        ev = (await it.__anext__()).events[0]
        print(f"  watch event: PUT {ev.kv.key.decode()} = "
              f"{ev.kv.value.decode()}")
        await q.put(None)
    task.abort()


async def s3_demo() -> None:
    server = s3_wire.WireServer()
    task = real.spawn(server.serve(("127.0.0.1", 0)))
    while server.bound_addr is None:
        if task.done():
            task.result()  # surface bind failures instead of hanging
        await real.sleep(0.005)
    host, port = server.bound_addr
    base = f"http://{host}:{port}"
    print(f"S3 REST serving on {base}")
    print(f"  (try: curl -X PUT {base}/demo; "
          f"curl -X PUT {base}/demo/k -d hi; curl {base}/demo/k)")

    try:
        import aiohttp
    except ImportError:
        print("  aiohttp not installed; skipping the client half")
        task.abort()
        return
    async with aiohttp.ClientSession() as http:
        await http.put(f"{base}/demo")
        r = await http.put(f"{base}/demo/greeting.txt", data=b"hello wire")
        print(f"  put object, ETag {r.headers['ETag']}")
        r = await http.get(f"{base}/demo/greeting.txt")
        print(f"  get object -> {await r.read()}")
        r = await http.get(f"{base}/demo?list-type=2")
        text = await r.text()
        print(f"  list-v2 -> {text[text.index('<Key>'):text.index('</Key>') + 6]}")
    task.abort()


async def main() -> None:
    await etcd_demo()
    await s3_demo()


if __name__ == "__main__":
    real.Runtime().block_on(main())
