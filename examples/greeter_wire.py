"""The greeter over GENUINE gRPC wire (HTTP/2 + protobuf via grpcio) —
the same proto-derived service class the simulator serves, reachable by
any stock gRPC client in any language (docs/real_mode.md; the analogue
of the reference's std mode being real tonic, madsim-tonic/src/lib.rs:1-8).

Run:  python examples/greeter_wire.py

Demonstrates both sides: the madsim GrpcioServer serving, then (a) the
madsim typed client and (b) a stock grpcio multicallable client — what
grpcio's generated stubs expand to — calling it over the real wire.
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from madsim_tpu import real
from madsim_tpu.grpc import protogen
from madsim_tpu.real import grpc

PROTO = """
syntax = "proto3";
package greeterwire;
message HelloRequest { string name = 1; }
message HelloReply { string message = 1; }
service Greeter {
  rpc SayHello (HelloRequest) returns (HelloReply);
  rpc LotsOfReplies (HelloRequest) returns (stream HelloReply);
}
"""


def build_pkg() -> protogen.ProtoPackage:
    d = tempfile.mkdtemp(prefix="greeter_wire")
    path = os.path.join(d, "greeterwire.proto")
    with open(path, "w") as f:
        f.write(PROTO)
    return protogen.compile_protos(path)


def make_greeter(pkg):
    HelloReply = pkg.messages["greeterwire.HelloReply"]

    @pkg.implement("greeterwire.Greeter")
    class Greeter:
        async def say_hello(self, request):
            return HelloReply(message=f"Hello {request.message.name}!")

        async def lots_of_replies(self, request):
            for i in range(3):
                yield HelloReply(message=f"{i}: Hello {request.message.name}!")

    return Greeter


async def main() -> None:
    pkg = build_pkg()
    HelloRequest = pkg.messages["greeterwire.HelloRequest"]
    HelloReply = pkg.messages["greeterwire.HelloReply"]

    # serve on an OS-assigned port
    router = grpc.GrpcioServer.builder().add_service(make_greeter(pkg)())
    serve = real.spawn(router.serve(("127.0.0.1", 0)))
    while router.bound_addr is None:
        if serve.done():
            serve.result()
        await real.sleep(0.005)
    host, port = router.bound_addr
    addr = f"{host}:{port}"
    print(f"serving genuine gRPC on {addr}")

    # (a) the madsim typed client over the real wire
    channel = grpc.GrpcioChannel(addr)
    client = grpc.GrpcioServiceClient(pkg.stub("greeterwire.Greeter"), channel)
    reply = await client.say_hello(HelloRequest(name="wire"))
    print("typed client:", reply.into_inner().message)
    stream = await client.lots_of_replies(HelloRequest(name="stream"))
    async for r in stream:
        print("typed client stream:", r.message)
    await channel.close()

    # (b) a STOCK grpcio client — no madsim code on this side
    from grpc import aio as grpc_aio

    async with grpc_aio.insecure_channel(addr) as ch:
        say_hello = ch.unary_unary(
            "/greeterwire.Greeter/SayHello",
            request_serializer=HelloRequest.SerializeToString,
            response_deserializer=HelloReply.FromString,
        )
        reply = await say_hello(HelloRequest(name="stock"))
        print("stock client:", reply.message)

    serve.abort()


if __name__ == "__main__":
    real.Runtime().block_on(main())
