"""Example: a tiny replicated KV service tested under deterministic simulation.

A primary node serves Put/Get RPCs; a flaky client hammers it while the test
harness injects faults (node kill/restart, link clog).  Run it:

    python examples/kv_store.py              # random seed sweep (5 seeds)
    MADSIM_TEST_SEED=7 python examples/kv_store.py   # replay one seed

The analogue of the reference's examples/rpc.rs demo
(/root/reference/madsim/examples/rpc.rs).
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import madsim_tpu as ms
from madsim_tpu.net import Endpoint, NetSim, Request
from madsim_tpu.plugin import simulator


class Put(Request):
    def __init__(self, key, value):
        self.key, self.value = key, value


class Get(Request):
    def __init__(self, key):
        self.key = key


def server_init():
    async def body():
        store = {}
        ep = await Endpoint.bind("10.0.0.100:50051")

        async def put(req):
            store[req.key] = req.value
            return "ok"

        async def get(req):
            return store.get(req.key)

        ep.add_rpc_handler(Put, put)
        ep.add_rpc_handler(Get, get)
        await ms.futures.pending_forever()

    return body()


async def scenario():
    h = ms.current_handle()
    seed = h.seed
    server = (
        h.create_node().name("kv-server").ip("10.0.0.100").init(server_init).build()
    )
    client = h.create_node().name("client").ip("10.0.0.200").build()
    net = simulator(NetSim)

    async def client_body():
        ep = await Endpoint.bind("0.0.0.0:0")
        await ms.sleep(0.5)
        ok = 0
        for i in range(20):
            try:
                await ep.call_timeout("10.0.0.100:50051", Put(f"k{i}", i), 2.0)
                ok += 1
            except ms.TimeoutError:
                pass
            await ms.sleep(0.2)
        return ok

    work = client.spawn(client_body())

    # fault schedule: clog the server for a while, then kill + restart it
    await ms.sleep(1.0)
    net.clog_node(server.id)
    await ms.sleep(1.0)
    net.unclog_node(server.id)
    await ms.sleep(0.5)
    h.kill(server)
    await ms.sleep(0.5)
    h.restart(server)

    ok = await work
    print(
        f"seed={seed} sim_time={ms.time.elapsed():.3f}s "
        f"puts_ok={ok}/20 msgs={net.stat().msg_count}"
    )
    assert ok >= 10, "too many failures even for this fault schedule"


if __name__ == "__main__":
    import os

    overrides = {}
    if "MADSIM_TEST_NUM" not in os.environ and "MADSIM_TEST_SEED" not in os.environ:
        overrides["count"] = 5  # default: a small sweep of fresh seeds
    ms.Builder.from_env(**overrides).run(scenario)
