"""The greeter service on REAL sockets — the same service class
`examples/greeter.py` runs inside the simulator, served over framed TCP
with no simulator involved (docs/real_mode.md; the analogue of building
the reference without `--cfg madsim`).

Run:  python examples/greeter_real.py
"""

from __future__ import annotations

import sys
from dataclasses import dataclass

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from madsim_tpu import real
from madsim_tpu.real import grpc


@real.codec.register
@dataclass
class HelloRequest:
    name: str
    delay_s: float = 0.0


@real.codec.register
@dataclass
class HelloReply:
    message: str


@grpc.service("helloworld.Greeter")
class Greeter:
    """Identical shape to the sim example — write once, run both modes."""

    @grpc.unary
    async def say_hello(self, request: grpc.Request) -> HelloReply:
        msg: HelloRequest = request.message
        if msg.delay_s:
            await real.sleep(msg.delay_s)
        if msg.name == "error":
            raise grpc.Status.invalid_argument("invalid name: error")
        return HelloReply(message=f"Hello {msg.name}!")

    @grpc.server_streaming
    async def lots_of_replies(self, request: grpc.Request):
        for i in range(3):
            yield HelloReply(message=f"{i}: Hello {request.message.name}!")

    @grpc.client_streaming
    async def lots_of_greetings(self, stream: grpc.Streaming) -> HelloReply:
        names = [m.name async for m in stream]
        return HelloReply(message=f"Hello {', '.join(names)}!")

    @grpc.bidi_streaming
    async def bidi_hello(self, stream: grpc.Streaming):
        async for m in stream:
            yield HelloReply(message=f"Hello {m.name}!")


async def demo() -> None:
    router = grpc.Server.builder().add_service(Greeter())
    serve = real.spawn(router.serve(("127.0.0.1", 0)))
    while router.bound_addr is None:
        if serve.done():
            serve.result()
        await real.sleep(0.005)
    addr = "%s:%d" % router.bound_addr
    print(f"serving on {addr} (real TCP)")

    channel = await grpc.Endpoint.from_static(f"http://{addr}").connect()
    client = grpc.ServiceClient(Greeter, channel)

    print("unary:", (await client.say_hello(HelloRequest(name="world"))).into_inner().message)
    stream = await client.lots_of_replies(HelloRequest(name="stream"))
    async for r in stream:
        print("server-stream:", r.message)
    reply = await client.lots_of_greetings(
        [HelloRequest(name="a"), HelloRequest(name="b"), HelloRequest(name="c")]
    )
    print("client-stream:", reply.into_inner().message)
    bidi = await client.bidi_hello([HelloRequest(name="x"), HelloRequest(name="y")])
    async for r in bidi:
        print("bidi:", r.message)
    try:
        await client.say_hello(HelloRequest(name="error"))
    except grpc.Status as e:
        print("error path:", e.code.name, "-", e.message)
    serve.abort()


if __name__ == "__main__":
    real.Runtime().block_on(demo())
