"""Host-tier Raft leader election — the MadRaft-style workload on the
Python executor.

This is the same workload as ``madsim_tpu.models.raft`` runs on the device
engine, written the way a *user* of the framework writes it: ordinary async
code on simulated nodes with Endpoint messaging, randomized election
timers on virtual time, and supervisor-injected crash/restarts (the shape
of the reference's tonic-example/etcd integration tests, SURVEY.md §4).

It doubles as the CPU baseline for ``bench.py``: seeds/sec here (one
Python-executor simulation per seed) vs seeds/sec of the lockstep TPU
sweep.

Run directly:  python examples/raft_host.py [seed]
"""

from __future__ import annotations

import sys
from typing import Dict, List

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import madsim_tpu as ms
from madsim_tpu import faults
from madsim_tpu.net import Endpoint
from madsim_tpu.oracle import HostRecorder
from madsim_tpu.oracle.history import OP_ELECT

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2
TAG = 1
PORT = 700

ELECTION_LO = 0.150
ELECTION_HI = 0.300
HEARTBEAT = 0.050
TICK = 0.010  # election-deadline poll granularity


def _ip(i: int) -> str:
    return f"10.0.0.{i + 1}"


class _Node:
    """Per-node volatile election state + message handlers."""

    def __init__(self, i: int, n: int, stats: Dict):
        self.i = i
        self.n = n
        self.stats = stats
        self.role = FOLLOWER
        self.term = 0
        self.voted = -1
        self.votes: set = set()
        self.deadline = ms.time.now_instant() + self._timeout()

    def _timeout(self) -> float:
        """Election timeout as THIS node's (possibly skewed) clock
        measures it: inside a clock-skew window the node's timers
        stretch by num/den — the host half of the device tier's
        ``engine.faults.skewed_delay`` (docs/faults.md gray failures)."""
        num, den = ms.time.node_skew()
        return ms.rand.uniform(ELECTION_LO, ELECTION_HI) * num / den

    def _reset_deadline(self) -> None:
        self.deadline = ms.time.now_instant() + self._timeout()

    async def _broadcast(self, ep: Endpoint, msg: tuple) -> None:
        for j in range(self.n):
            if j != self.i:
                await ep.send_to_raw((_ip(j), PORT), TAG, msg)
                self.stats["msgs"] += 1

    async def _become_leader(self, ep: Endpoint) -> None:
        self.role = LEADER
        self.stats["elections"].append((self.term, self.i))
        rec = self.stats.get("recorder")
        if rec is not None:
            # same row the device model's record hook writes: one
            # OP_ELECT invoke per won election (client = node, key =
            # term) — checkable by oracle.specs.ElectionSpec on either
            # tier (explore/differential.py)
            rec.invoke(client=self.i, op=OP_ELECT, key=self.term, inp=self.i)
        for term, who in self.stats["elections"]:
            if term == self.term and who != self.i:
                self.stats["violations"] += 1
        await self._broadcast(ep, ("ae", self.term, self.i))

    async def handle(self, ep: Endpoint, msg: tuple) -> None:
        kind, mterm, src = msg
        if mterm > self.term:
            self.term, self.role, self.voted = mterm, FOLLOWER, -1
            self.votes = set()
        if kind == "rv":
            if mterm == self.term and self.voted in (-1, src):
                self.voted = src
                self._reset_deadline()
                await ep.send_to_raw((_ip(src), PORT), TAG, ("vg", mterm, self.i))
                self.stats["msgs"] += 1
        elif kind == "vg":
            if self.role == CANDIDATE and mterm == self.term:
                self.votes.add(src)
                if len(self.votes) >= self.n // 2 + 1:
                    await self._become_leader(ep)
        elif kind == "ae":
            if mterm == self.term:
                if self.role == CANDIDATE:
                    self.role = FOLLOWER
                self._reset_deadline()

    async def receiver(self, ep: Endpoint) -> None:
        while True:
            msg, _src = await ep.recv_from_raw(TAG)
            await self.handle(ep, msg)

    async def ticker(self, ep: Endpoint) -> None:
        """Election timer (poll) + leader heartbeats."""
        while True:
            if self.role == LEADER:
                await ms.sleep(HEARTBEAT)
                await self._broadcast(ep, ("ae", self.term, self.i))
            else:
                await ms.sleep(TICK)
                if ms.time.now_instant() >= self.deadline:
                    self.term += 1
                    self.role = CANDIDATE
                    self.voted = self.i
                    self.votes = {self.i}
                    self._reset_deadline()
                    await self._broadcast(ep, ("rv", self.term, self.i))


def _node_init(i: int, n: int, stats: Dict):
    def make():
        async def run():
            node = _Node(i, n, stats)
            ep = await Endpoint.bind((_ip(i), PORT))
            ms.spawn(node.receiver(ep))
            await node.ticker(ep)

        return run()

    return make


async def _supervise(stats: Dict, n: int, crashes: int, sim_seconds: float) -> None:
    h = ms.current_handle()
    nodes: List = [
        h.create_node().name(f"raft-{i}").ip(_ip(i)).init(_node_init(i, n, stats)).build()
        for i in range(n)
    ]
    deadline = ms.time.now_instant() + sim_seconds
    for _ in range(crashes):
        at = ms.rand.uniform(0.0, sim_seconds / 2)
        victim = nodes[ms.rand.gen_range(0, n)]
        await ms.sleep(max(at - ms.time.elapsed(), 0.001))
        h.kill(victim)
        await ms.sleep(ms.rand.uniform(0.1, 1.0))
        h.restart(victim)
    remaining = deadline - ms.time.now_instant()
    if remaining > 0:
        await ms.sleep(remaining)


def _fresh_stats() -> Dict:
    """Run stats + the op-history recorder (oracle.HostRecorder): every
    run emits a checkable election history alongside the counters, so
    the differential harness (explore/differential.py) can check host
    and device histories against the same sequential spec."""
    return {
        "elections": [],
        "violations": 0,
        "msgs": 0,
        "recorder": HostRecorder(),
    }


def _finish_stats(stats: Dict, seed: int) -> Dict:
    stats["seed"] = seed
    stats["leaders_elected"] = len(stats["elections"])
    stats["history"] = stats.pop("recorder").history(seed)
    return stats


def run_seed(
    seed: int, n: int = 5, crashes: int = 1, sim_seconds: float = 3.0
) -> Dict:
    """One complete simulation; returns election stats for the seed."""
    stats = _fresh_stats()
    rt = ms.Runtime(seed=seed)
    rt.block_on(_supervise(stats, n, crashes, sim_seconds))
    return _finish_stats(stats, seed)


async def _supervise_plan(
    stats: Dict, n: int, plan, sim_seconds: float, spec=None
) -> None:
    """Supervisor that applies a *recorded* fault schedule (from a
    device-tier trace or ``faults.compile_host``) instead of drawing its
    own faults — the shared ``madsim_tpu.faults.apply_schedule``
    supervisor, which mirrors the device tier's edge-gated semantics
    (restarting a live node is a no-op on both tiers)."""
    h = ms.current_handle()
    nodes: List = [
        h.create_node().name(f"raft-{i}").ip(_ip(i)).init(_node_init(i, n, stats)).build()
        for i in range(n)
    ]
    await faults.apply_schedule(plan, nodes, spec=spec)
    remaining = sim_seconds - ms.time.elapsed()
    if remaining > 0:
        await ms.sleep(remaining)


def run_seed_with_plan(
    seed: int, plan, n: int = 5, sim_seconds: float = 3.0, spec=None,
    extend: bool = True,
) -> Dict:
    """One simulation with the recorded faults at the recorded virtual
    times.

    The cross-tier replay target: a device-found seed's fault schedule
    re-applied to this ordinary async implementation, debugger-attachable.
    By default the run extends at least one second past the last planned
    fault so the cluster gets a post-fault observation window even when
    the plan outlives ``sim_seconds``; pass ``extend=False`` to hard-stop
    at ``sim_seconds`` instead (the differential harness does — a matched
    host↔device grid needs matched horizons, and the device tier stops
    at its ``time_limit_ns`` regardless of the schedule). ``spec`` is
    only needed when the schedule contains latency/loss burst or
    clock-skew events.
    """
    stats = _fresh_stats()
    end_s = sim_seconds
    if plan and extend:
        end_s = max(end_s, max(t for t, _, _ in plan) / 1e9 + 1.0)
    elif plan and not extend:
        plan = [e for e in plan if e[0] / 1e9 < sim_seconds]
    rt = ms.Runtime(seed=seed)
    rt.block_on(_supervise_plan(stats, n, plan, end_s, spec=spec))
    return _finish_stats(stats, seed)


def run_seed_with_spec(
    seed: int, spec, campaign_seed: int, n: int = 5, sim_seconds: float = 3.0,
    extend: bool = True,
) -> Dict:
    """One simulation under a declarative fault campaign: the SAME
    ``FaultSpec`` + ``campaign_seed`` a device-tier sweep lane compiles
    (models/raft.py ``fault_spec``), applied to this ordinary async
    implementation — no trace hop needed."""
    plan = faults.compile_host(spec, n, campaign_seed)
    return run_seed_with_plan(
        seed, plan, n=n, sim_seconds=sim_seconds, spec=spec, extend=extend
    )


if __name__ == "__main__":
    seed = int(sys.argv[1]) if len(sys.argv) > 1 else 1
    out = run_seed(seed)
    print(
        f"seed={seed} elections={out['leaders_elected']} "
        f"violations={out['violations']} msgs={out['msgs']}"
    )
