"""The greeter service — port of the reference's end-to-end gRPC app
(tonic-example/src/lib.rs:22-123): unary with delay + error paths, server
streaming, client streaming, and bidirectional streaming.

Used by tests/test_grpc.py (the analogue of tonic-example/tests/test.rs)
and runnable standalone:  python examples/greeter.py
"""

from __future__ import annotations

import sys
from dataclasses import dataclass

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import madsim_tpu as ms
from madsim_tpu import grpc


@dataclass
class HelloRequest:
    name: str
    delay_s: float = 0.0


@dataclass
class HelloReply:
    message: str


@grpc.service("helloworld.Greeter")
class Greeter:
    """The test service (ref tonic-example/src/lib.rs:22-123)."""

    @grpc.unary
    async def say_hello(self, request: grpc.Request) -> HelloReply:
        msg: HelloRequest = request.message
        if msg.delay_s:
            await ms.sleep(msg.delay_s)
        if msg.name == "error":
            raise grpc.Status.invalid_argument("invalid name: error")
        return HelloReply(message=f"Hello {msg.name}!")

    @grpc.server_streaming
    async def lots_of_replies(self, request: grpc.Request):
        msg: HelloRequest = request.message
        for i in range(3):
            await ms.sleep(0.1)
            yield HelloReply(message=f"{i}: Hello {msg.name}!")

    @grpc.client_streaming
    async def lots_of_greetings(self, stream: grpc.Streaming) -> HelloReply:
        names = []
        async for msg in stream:
            names.append(msg.name)
        return HelloReply(message=f"Hello {', '.join(names)}!")

    @grpc.bidi_streaming
    async def bidi_hello(self, stream: grpc.Streaming):
        async for msg in stream:
            yield HelloReply(message=f"Hello {msg.name}!")


async def serve(addr: str = "10.0.0.1:50051") -> None:
    await grpc.Server.builder().add_service(Greeter()).serve(addr)


async def demo() -> None:
    h = ms.current_handle()
    h.create_node().name("server").ip("10.0.0.1").init(lambda: serve()).build()
    client = h.create_node().name("client").ip("10.0.0.2").build()

    async def run_client():
        channel = await grpc.Endpoint.from_static("http://10.0.0.1:50051").connect()
        c = grpc.ServiceClient(Greeter, channel)
        reply = await c.say_hello(HelloRequest(name="world"))
        print("unary:", reply.into_inner().message)
        stream = await c.lots_of_replies(HelloRequest(name="stream"))
        async for r in stream:
            print("server-stream:", r.message)

    await ms.sleep(0.1)
    await client.spawn(run_client())


if __name__ == "__main__":
    ms.Runtime(seed=1).block_on(demo())
